"""Total-order sort — the TeraSort-shaped dataflow workload (ROADMAP
item 1; Coded TeraSort, arXiv:1702.04850, PAPERS.md).

Everything the framework ran before this module is key-AGGREGATION
shaped: the reduce collapses each key's rows and the output order is
incidental.  A total-order sort inverts that — every input row survives
and the *order* is the product — which exercises the Mapper/Reducer
machinery from a new angle:

    sample keys  ->  range splitters (S-1 quantiles, identical on every
    process)  ->  route rows to their owner shard over the SAME
    ``all_to_all`` exchange the reduce engines use (range partition
    instead of hash buckets: :func:`parallel.shuffle.range_dest`)  ->
    per-shard ``lax.sort``  ->  ordered shard writes whose concatenation
    is globally sorted.

Record model: fixed-width binary (u64 key, u64 payload) rows — a
``.npy`` array of shape ``(n, 2)`` (column 0 the key) or ``(n,)``
(keys only; the payload defaults to the global row index, making every
record distinct and the sort stable-by-construction).  The dataflow
workloads deliberately share this 16-byte record with the shuffle
layer's on-disk format (:class:`map_oxidize_tpu.shuffle.disk.DiskPairStage`),
so a beyond-RAM sort stages in the SAME top-bits disk buckets the pair
collect spills to — and because buckets are top-bit key RANGES, the
bucket-by-bucket drain (with a full (key, payload) lexsort per bucket)
IS the total order, no extra merge pass.

This module owns the host-side pieces the drivers
(:mod:`map_oxidize_tpu.runtime.dataflow`,
:mod:`map_oxidize_tpu.parallel.dataflow`) and the property suite share:
record IO, the sampled range partitioner, and the NumPy oracle.
"""

from __future__ import annotations

import os

import numpy as np

#: the one key value the engines cannot carry: both u32 planes equal to
#: the padding SENTINEL (0xFFFFFFFF_FFFFFFFF) — a real row with this key
#: would be masked out as padding after the exchange.  The drivers
#: refuse it loudly per chunk instead of silently dropping the row.
RESERVED_KEY = np.uint64(0xFFFFFFFFFFFFFFFF)

#: on-disk sorted-output record: little-endian (u64 key, u64 payload) —
#: byte-compatible with the shuffle layer's spill record, so part files
#: concatenate into one valid record stream
OUT_REC = np.dtype([("k", "<u8"), ("p", "<u8")])


def load_records(path: str):
    """Memory-map a records ``.npy``: returns ``(keys, payloads, n)``
    with ``keys`` a ``(n,)`` u64 view and ``payloads`` a ``(n,)`` u64
    view or ``None`` (keys-only input — consumers synthesize the global
    row index).  Accepts u64 or i64 storage (i64 is VIEWED as u64: the
    record model is 64 raw bits, not a signed quantity)."""
    arr = np.load(path, mmap_mode="r")
    if arr.dtype not in (np.dtype(np.uint64), np.dtype(np.int64)):
        raise ValueError(
            f"dataflow records must be uint64 (or int64, viewed as raw "
            f"bits); got dtype {arr.dtype} in {path!r}")
    if arr.ndim == 1:
        return arr.view(np.uint64), None, int(arr.shape[0])
    if arr.ndim == 2 and arr.shape[1] == 2:
        a = arr.view(np.uint64)
        return a[:, 0], a[:, 1], int(arr.shape[0])
    raise ValueError(
        f"dataflow records must be (n,) keys or (n, 2) (key, payload) "
        f"rows; got shape {arr.shape} in {path!r}")


def iter_record_chunks(path: str, rows_per_chunk: int, proc: int = 0,
                       n_proc: int = 1):
    """Yield this process's record chunks (chunk ``i % n_proc == proc``)
    as ``(keys, payloads, end_row)`` — materialized u64 arrays (the mmap
    slice copies), payloads synthesized as the GLOBAL row index for
    keys-only inputs.  Every process iterates the same deterministic
    chunk plan, so no coordination divides the input (the same contract
    as :func:`parallel.distributed._local_chunks`)."""
    keys, payloads, n = load_records(path)
    rows = max(1, rows_per_chunk)
    for ci, start in enumerate(range(0, n, rows)):
        stop = min(start + rows, n)
        if ci % n_proc != proc:
            continue
        k = np.ascontiguousarray(keys[start:stop])
        if bool((k == RESERVED_KEY).any()):
            raise ValueError(
                f"input contains the reserved key "
                f"{int(RESERVED_KEY):#018x} (the engine padding "
                "sentinel); dataflow records must avoid exactly this "
                "one value")
        if payloads is None:
            p = np.arange(start, stop, dtype=np.uint64)
        else:
            p = np.ascontiguousarray(payloads[start:stop])
        yield k, p, stop


# --- the sampled range partitioner -----------------------------------------


def compute_splitters(sample: np.ndarray, num_shards: int) -> np.ndarray:
    """``num_shards - 1`` ascending u64 splitter keys from a key sample:
    the sorted sample's ``i/S`` quantiles.  Shard ``s`` then owns keys
    in ``[splitters[s-1], splitters[s])`` with ties broken
    deterministically toward the RIGHT shard (a key equal to splitter
    ``j`` lands on shard ``j+1`` — see :func:`range_partition`).

    Properties the suite pins on adversarial inputs (skew, duplicate
    floods, empty samples): the splitters are nondecreasing, the induced
    partition covers every u64 exactly once, and the shard index is
    monotone in the key.  A duplicate-heavy sample may yield EQUAL
    splitters — empty shards, which are valid (and what extreme skew
    honestly deserves); an EMPTY sample falls back to evenly spaced
    u64-space splitters so the partition still covers."""
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    S = num_shards
    if S == 1:
        return np.empty(0, np.uint64)
    sample = np.asarray(sample, np.uint64).ravel()
    if sample.size == 0:
        return np.array([(i * (1 << 64)) // S for i in range(1, S)],
                        dtype=np.uint64)
    srt = np.sort(sample)
    idx = (np.arange(1, S, dtype=np.int64) * srt.size) // S
    return srt[idx].copy()


def range_partition(keys: np.ndarray, splitters: np.ndarray) -> np.ndarray:
    """Owner shard per key under the range partition — the HOST spelling
    of :func:`parallel.shuffle.range_dest` (the in-trace one), and the
    pair the property suite holds bit-identical: the count of splitters
    ``<=`` key, i.e. ``searchsorted(splitters, key, side='right')``."""
    return np.searchsorted(np.asarray(splitters, np.uint64),
                           np.asarray(keys, np.uint64),
                           side="right").astype(np.int64)


def sample_keys(path: str, target: int) -> np.ndarray:
    """Deterministic strided key sample of the WHOLE file: identical on
    every process by construction (the input is visible to every host —
    the same shared-storage contract distributed k-means already has),
    so distributed splitters need no collective.  Strided rather than
    random: quantiles of an every-kth-row sample converge the same way
    and reproduce bit-for-bit."""
    keys, _payloads, n = load_records(path)
    if n == 0:
        return np.empty(0, np.uint64)
    stride = max(1, n // max(1, target))
    return np.ascontiguousarray(keys[::stride])


# --- oracle + output -------------------------------------------------------


def sort_model(keys: np.ndarray, payloads: np.ndarray):
    """Pure-NumPy oracle: rows sorted by (key, payload), both compared
    as u64.  Independent of every engine under test."""
    keys = np.asarray(keys, np.uint64)
    payloads = np.asarray(payloads, np.uint64)
    order = np.lexsort((payloads, keys))
    return keys[order], payloads[order]


def write_sorted_records(path: str, runs) -> int:
    """Stream sorted ``(keys, docs)`` runs to ``path`` as
    :data:`OUT_REC` records (atomic: temp + rename).  One run is
    resident at a time — the spilled drain hands one disk bucket per
    run, so a beyond-RAM sort writes with bounded memory.  Returns the
    row count written."""
    n = 0
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        for keys, docs in runs:
            rec = np.empty(keys.shape[0], OUT_REC)
            rec["k"] = np.asarray(keys, np.uint64)
            rec["p"] = np.asarray(docs).view(np.uint64)
            f.write(rec.tobytes())
            n += int(keys.shape[0])
    os.replace(tmp, path)
    return n


def read_sorted_records(path: str):
    """Read an :data:`OUT_REC` artifact back as ``(keys, payloads)``
    u64 arrays (tests and the smoke assertions)."""
    rec = np.fromfile(path, OUT_REC)
    return rec["k"].copy(), rec["p"].copy()
