"""Word count — the reference's one and only workload.

Mapper semantics follow ``/root/reference/src/main.rs:94-101`` exactly:
whitespace-split, lowercase, **no punctuation stripping** ("the," and "the"
are distinct keys).  Two tokenizer modes:

* ``ascii`` (default): byte-level — split on ASCII whitespace, lowercase
  ASCII letters.  ``bytes.split()`` / ``bytes.lower()`` are the exact Python
  equivalents of the C++ hot loop, so native and fallback paths stay
  bit-identical.
* ``unicode``: decode UTF-8 and use ``str.split()`` / ``str.lower()`` —
  matching Rust ``split_whitespace()`` + ``to_lowercase()`` (main.rs:96-97)
  for Unicode corpora.  The C++ loop accelerates this mode too, via a UTF-8
  transform pass whose tables are generated from Python's own str.lower() /
  str.isspace() (tests/test_unicode_native.py proves bit-parity).  (Known
  delta: a handful of locale-ish case mappings, e.g. İ, differ between Rust
  and Python; both are Unicode-correct and no English corpus contains them.)

The mapper is a *combiner*: it counts within the chunk (as the reference's
per-chunk ``HashMap`` effectively does) and emits one row per distinct token,
shrinking host->HBM traffic by the chunk's duplication factor.
"""

from __future__ import annotations

from collections import Counter

import numpy as np

from map_oxidize_tpu.api import Mapper, MapOutput, SumReducer
from map_oxidize_tpu.ops.hashing import HashDictionary, moxt64_bytes, split_u64


def tokenize(chunk, mode: str = "ascii") -> list[bytes]:
    """Split + lowercase, per reference semantics (main.rs:96-97)."""
    if not isinstance(chunk, bytes):
        chunk = bytes(chunk)  # splitter may yield memoryviews
    if mode == "ascii":
        return chunk.lower().split()
    if mode == "unicode":
        return [t.encode("utf-8") for t in chunk.decode("utf-8").lower().split()]
    raise ValueError(f"unknown tokenizer mode {mode!r}")


class WordCountMapper(Mapper):
    value_shape = ()
    value_dtype = np.int32
    keys_have_dictionary = True

    def __init__(self, tokenizer: str = "ascii", use_native: bool = True):
        self.tokenizer = tokenizer
        self.use_native = use_native
        self._native = None
        if self.use_native:
            from map_oxidize_tpu.native import bindings

            self._native = bindings.stream_or_none(ngram=1,
                                                   tokenizer=tokenizer)

    def map_file(self, path: str, chunk_bytes: int, start_offset: int = 0):
        """Native mmap fast path: a ``(MapOutput, next_offset)`` generator
        over the file, or None when the C++ loop is unavailable (driver falls
        back to the streaming splitter + map_chunk)."""
        if self._native is None:
            return None
        return self._native.iter_file(path, chunk_bytes, start_offset)

    def map_chunk(self, chunk: bytes) -> MapOutput:
        if self._native is not None:
            # dictionary carries only the delta of newly seen keys — the
            # driver's per-chunk dictionary.update() accumulates the union
            return self._native.map_chunk(chunk)
        toks = tokenize(chunk, self.tokenizer)
        counts = Counter(toks)
        d = HashDictionary()
        hashes = np.empty(len(counts), np.uint64)
        values = np.empty(len(counts), np.int32)
        for i, (tok, c) in enumerate(counts.items()):
            h = moxt64_bytes(tok)
            d.add(h, tok)
            hashes[i] = h
            values[i] = c
        hi, lo = split_u64(hashes)
        return MapOutput(hi=hi, lo=lo, values=values, dictionary=d,
                         records_in=len(toks))


def make_wordcount(tokenizer: str = "ascii", use_native: bool = True):
    """(mapper, reducer) pair for the word-count workload."""
    return WordCountMapper(tokenizer, use_native), SumReducer()
