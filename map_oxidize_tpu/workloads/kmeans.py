"""k-means iteration — BASELINE.json config #5 (no reference implementation
exists; the reference's only workload is word count, /root/reference/src/
main.rs:94-101, so semantics are defined here).

MapReduce formulation (the reduce is exactly the reference's merge shape,
main.rs:131-134, generalized from ``+=`` on ints to ``+=`` on vectors):

    map:    point -> (nearest centroid id, [x_0..x_{d-1}, 1])
    reduce: per-key vector sum
    emit:   new centroid c_k = sum_k[:d] / sum_k[d]

Keys are small integers, not strings — ``hi = 0, lo = centroid_id`` with no
dictionary (``keys_have_dictionary = False``), which is the point of the
64-bit key design: integer-keyed workloads ride the same engine as hashed
string keys.

Two implementations:

* :class:`KMeansMapper` + :func:`kmeans_iteration` — the streaming path:
  points stream through the host mapper (vectorized NumPy assign + per-chunk
  partial sums, a combiner like the word-count mapper), the device engine
  folds ``(d+1,)`` vector values.  Works on any engine including the sharded
  all_to_all one.
* :func:`kmeans_fit_device` — the TPU-natural path: points are put in HBM
  ONCE and every iteration runs device-side (distance matmul on the MXU,
  one-hot matmul partial sums, no per-iteration host traffic).  On the
  measured deployment the host->device link is ~26-37 MB/s, so amortizing
  the single transfer over many iterations is what makes the device path
  win; see also parallel.kmeans for the multi-chip version.

Input convention: a ``.npy`` file of float32 ``(n, d)`` points (memory-mapped
and streamed by row ranges — the corpus never sits in host RAM).
"""

from __future__ import annotations

import numpy as np

from map_oxidize_tpu.api import Mapper, MapOutput, SumReducer


def assign_points(points: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Nearest-centroid ids, vectorized: argmin_k ||p||^2 - 2 p.C^T + ||c||^2
    (the ||p||^2 term is constant per point and dropped)."""
    d2 = -2.0 * points @ centroids.T + (centroids * centroids).sum(1)
    return np.argmin(d2, axis=1).astype(np.int32)


class KMeansMapper(Mapper):
    """Chunk of points -> per-centroid partial ``[sum_x..., count]`` rows."""

    value_dtype = np.float32
    keys_have_dictionary = False

    def __init__(self, centroids: np.ndarray):
        self.centroids = np.asarray(centroids, np.float32)
        self.k, self.d = self.centroids.shape
        self.value_shape = (self.d + 1,)

    def map_chunk(self, points) -> MapOutput:
        points = np.asarray(points, np.float32)
        n = points.shape[0]
        if n == 0:
            return MapOutput(hi=np.empty(0, np.uint32),
                             lo=np.empty(0, np.uint32),
                             values=np.empty((0, self.d + 1), np.float32),
                             records_in=0)
        cid = assign_points(points, self.centroids)
        # per-chunk combine: one row per non-empty centroid (bincount per
        # dimension is O(n*d) with no Python-per-point work)
        sums = np.empty((self.k, self.d + 1), np.float32)
        for j in range(self.d):
            sums[:, j] = np.bincount(cid, weights=points[:, j],
                                     minlength=self.k)
        counts = np.bincount(cid, minlength=self.k)
        sums[:, self.d] = counts
        live = counts > 0
        ids = np.nonzero(live)[0].astype(np.uint32)
        return MapOutput(hi=np.zeros(ids.shape[0], np.uint32), lo=ids,
                         values=sums[live], records_in=n)


def iter_point_chunks(path: str, rows_per_chunk: int):
    """Stream ``(n, d)`` float32 rows from a .npy file without loading it
    (np.load memory-maps; slices fault in lazily)."""
    pts = np.load(path, mmap_mode="r")
    for start in range(0, pts.shape[0], rows_per_chunk):
        yield np.asarray(pts[start:start + rows_per_chunk], np.float32)


def kmeans_iteration(engine, centroids: np.ndarray, chunks,
                     mapper: "KMeansMapper | None" = None,
                     mapped=None) -> np.ndarray:
    """One streamed iteration: feed every chunk's partial sums through the
    engine, reduce on device, return updated centroids.  Empty centroids
    keep their previous position (documented choice; the reference has no
    analogous case).

    ``mapped`` (an iterable of MapOutputs) replaces the chunk+map loop
    when the caller runs the host assign elsewhere — the driver passes a
    prefetch-pipelined map stream here so assigning chunk i+1 overlaps
    chunk i's engine feed."""
    centroids = np.asarray(centroids, np.float32)
    if mapped is None:
        if mapper is None:
            mapper = KMeansMapper(centroids)
        mapped = (mapper.map_chunk(chunk) for chunk in chunks)
    n_points = 0
    for out in mapped:
        n_points += out.records_in
        engine.feed(out)
    hi, lo, vals, n = engine.finalize()
    hi = np.asarray(hi)
    lo = np.asarray(lo)
    vals = np.asarray(vals)
    live = ~(hi == np.uint32(0xFFFFFFFF))  # SENTINEL hi plane marks padding
    ids = lo[live].astype(np.int64)
    sums = vals[live]
    new = centroids.copy()
    counts = sums[:, -1]
    # conservation: every point lands in exactly one centroid's count.
    # Counts fold on device as float32, which rounds once a cluster passes
    # 2^24 points — so the check is tolerance-based, not exact, to avoid
    # killing numerically fine streamed jobs at scale.
    total = float(np.asarray(counts, np.float64).sum())
    if n_points and abs(total - n_points) > max(1.0, 1e-4 * n_points):
        raise RuntimeError(
            f"k-means conservation violated: {n_points} points in, "
            f"{total} counted")
    nz = counts > 0
    new[ids[nz]] = sums[nz, :-1] / counts[nz, None]
    return new


def kmeans_model(points: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """NumPy oracle: one full-batch iteration (independent of the engine)."""
    points = np.asarray(points, np.float32)
    centroids = np.asarray(centroids, np.float32)
    cid = assign_points(points, centroids)
    new = centroids.copy()
    for k in range(centroids.shape[0]):
        m = cid == k
        if m.any():
            new[k] = points[m].mean(0)
    return new


def kmeans_fit_device(points, centroids, iters: int = 1, device=None,
                      on_iter=None, timings: dict | None = None,
                      precision: str = "highest"):
    """HBM-resident k-means: points transfer once, ``iters`` iterations run
    entirely on device (distance matmul + one-hot matmul partial sums — both
    MXU work).  Returns the final centroids as NumPy.

    ``on_iter(i, centroids_np)`` (checkpoint hook): when given, iterations
    step one at a time python-side — points stay in HBM, only the tiny
    ``(k, d)`` centroids cross back per iteration — and the hook sees the
    state after each.  The per-step jit runs the same compiled body the
    ``fori_loop`` path runs, so enabling checkpointing costs one dispatch
    per iteration, not a different computation.

    ``timings`` (when a dict is passed) receives ``transfer_s`` (host->HBM
    put of the points, the one-time cost iterations amortize) and
    ``iter_s`` (the full iteration chain, fetch-forced — the compute-bound
    region an MFU figure should be computed over)."""
    import time
    import jax

    points = np.asarray(points, np.float32)
    k = np.asarray(centroids, np.float32).shape[0]

    if device is None:
        device = jax.devices()[0]
    if precision == "bf16":
        # store the points bf16 in HBM: every iteration re-reads the whole
        # array and the workload is HBM-read-bound (60 GB/s achievable,
        # measured round 5 — a plain jnp.sum over 512MB), so half the
        # bytes is half the iteration; the matmul operand was cast to
        # bf16 anyway, so the numerics are unchanged.  Bonus: half the
        # host->device transfer on the session-variable link.
        import ml_dtypes

        points = points.astype(ml_dtypes.bfloat16)
    t0 = time.perf_counter()
    p_dev = jax.device_put(points, device)
    p_dev.block_until_ready()
    if timings is not None:
        timings["transfer_s"] = time.perf_counter() - t0
    c_dev = jax.device_put(np.asarray(centroids, np.float32), device)
    t0 = time.perf_counter()
    if on_iter is None:
        # asarray forces the chain (block_until_ready is not reliable for
        # computed results on the remote-attach platform)
        out = np.asarray(_kmeans_fit(c_dev, p_dev, k, iters, precision))
        if timings is not None:
            timings["iter_s"] = time.perf_counter() - t0
        return out
    c = c_dev
    for i in range(iters):
        c = _kmeans_step(c, p_dev, k, precision)
        on_iter(i + 1, np.asarray(c))
    # no iter_s here: this loop interleaves per-iteration readback and the
    # caller's snapshot I/O, so it is NOT the compute-bound region the
    # docstring promises — an MFU computed over it would be wrong
    return np.asarray(c)


def assign_and_sum(p, c, k: int, precision: str = "highest", w=None):
    """Shared numerics of one k-means iteration (single-device AND sharded
    steps import this, so the two paths cannot drift): distance matmul ->
    argmin assignment -> one-hot partial-sum matmul.  Returns
    ``(sums (k, d), counts (k,))`` — per-shard partials in the sharded
    case (``w``: 0/1 row weights so padding never moves a centroid).

    ``precision``:

    * ``"highest"`` — f32 operands, ``Precision.HIGHEST`` matmuls (the
      MXU emulates f32 with multiple bf16 passes; the oracle-parity mode).
    * ``"bf16"`` — matmul operands cast to bfloat16 with f32 accumulation
      (``preferred_element_type``): ONE native MXU pass per matmul, the
      rate the chip is built for.  One-hot/weight values are 0/1 (exact
      in bf16) and accumulation stays f32, so only the distance ranking
      and each point's bf16 rounding perturb the result — bounded by the
      convergence-parity test and the bench drift gate.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if precision == "bf16":
        # p may ALREADY be stored bf16 in HBM (the fit paths put it there:
        # this workload is HBM-read-bound — measured 60 GB/s achievable on
        # the round-5 chip — so halving the bytes halves the iteration)
        pm, cm = p.astype(jnp.bfloat16), c.astype(jnp.bfloat16)

        def dot(a, b):
            return jnp.dot(a, b, preferred_element_type=jnp.float32)
    elif precision == "highest":
        pm, cm = p, c

        def dot(a, b):
            return jnp.dot(a, b, precision=lax.Precision.HIGHEST)
    else:
        raise ValueError(f"unknown kmeans precision {precision!r}")
    # squared-norm term stays f32 in both modes (cheap, no matmul)
    d2 = -2.0 * dot(pm, cm.T) + (c * c).sum(1)
    cid = jnp.argmin(d2, axis=1)
    # one-hot/counts accumulate in f32 ALWAYS: a bf16 count saturates at
    # 256 (8 mantissa bits) — only the matmul operand is cast down
    onehot = jax.nn.one_hot(cid, k, dtype=jnp.float32)       # (n, k)
    if w is not None:
        onehot = onehot * w[:, None]
    sums = dot(onehot.astype(pm.dtype).T, pm)                # (k, d) on MXU
    counts = onehot.sum(0)
    return sums, counts


def _kmeans_step_impl(c, p, k: int, precision: str = "highest"):
    import jax.numpy as jnp

    sums, counts = assign_and_sum(p, c, k, precision)
    return jnp.where(counts[:, None] > 0,
                     sums / jnp.maximum(counts[:, None], 1.0), c)


def _make_jitted():
    # module-level jit wrappers: the SAME function objects persist across
    # kmeans_fit_device calls, so a warm call followed by a timed call
    # hits the in-process executable cache instead of re-tracing (a fresh
    # closure per call re-compiled every run — ~tens of seconds through
    # the tunnel — and polluted the timed region)
    import functools

    import jax
    from jax import lax

    from map_oxidize_tpu.obs.compile import observed_jit

    step = observed_jit("kmeans/step",
                        jax.jit(_kmeans_step_impl, static_argnums=(2, 3)))

    @functools.partial(observed_jit, "kmeans/fit")
    @functools.partial(jax.jit, static_argnums=(2, 3, 4))
    def fit(c, p, k, iters, precision):
        return lax.fori_loop(
            0, iters,
            lambda _, cc: _kmeans_step_impl(cc, p, k, precision), c)

    return step, fit


class _Lazy:
    """Defer the jax import until the device path actually runs."""

    step = None
    fit = None


def _kmeans_step(c, p, k, precision="highest"):
    if _Lazy.step is None:
        _Lazy.step, _Lazy.fit = _make_jitted()
    return _Lazy.step(c, p, k, precision)


def _kmeans_fit(c, p, k, iters, precision="highest"):
    if _Lazy.fit is None:
        _Lazy.step, _Lazy.fit = _make_jitted()
    return _Lazy.fit(c, p, k, iters, precision)


def kmeans_fit_streamed_device(path: str, centroids: np.ndarray,
                               iters: int = 1, chunk_rows: int = 1 << 21,
                               device=None, precision: str = "highest",
                               timings: dict | None = None, on_iter=None,
                               pipeline_depth: int = 2, obs=None,
                               dispatch_batch: int = 0):
    """Beyond-HBM k-means with DEVICE assignment: points stream through
    the chip in fixed-row chunks each iteration — SURVEY §7 hard part
    (c)'s double-buffered formulation, now the 1-device mesh case of
    :func:`map_oxidize_tpu.parallel.kmeans.kmeans_fit_streamed` (the
    psum over a singleton shard axis degenerates, so single-device and
    sharded streaming run the SAME jitted program and cannot drift).
    The host block prep (fault-in + pad + cast) runs in a bounded
    prefetch thread (``pipeline_depth``) so preparing chunk i+1 overlaps
    chunk i's transfer+MXU work; ``device_put`` and the step dispatch
    are already async, and the ``(k, d+1)`` accumulator is donated
    across chunk steps, so only the tiny centroid update crosses back
    per iteration.

    Contrast :func:`kmeans_iteration` (host-assign streaming: the NumPy
    assign competes with the baseline on the same core) and
    :func:`kmeans_fit_device` (points resident in HBM — the right call
    whenever they fit).  This path is LINK-BOUND by construction: its
    ceiling is link_bytes_per_s / (4d bytes/point) per iteration (half
    that in bf16 mode — the chunk is cast before the put), which on the
    measured session-variable link (50-1200 MB/s, RESULTS.md) brackets
    the NumPy baseline from both sides; benchmarks record both regimes.

    ``timings``: ``feed_s`` (host wall of the full chunk loop, transfer
    included) plus the prefetcher's ``feed_wait_s``/``overlap_ratio``;
    there is no transfer/compute split to report because overlap is the
    point.

    Dispatch economy is the design driver on the measured deployment:
    each separately launched executable costs ~150-250 ms through the
    remote-attach tunnel regardless of size (the round-3 fetch-cost note,
    runtime/collect.py, re-measured round 5), so one iteration is exactly
    ``ceil(n_chunks / B)`` dispatches — ``dispatch_batch`` (B) chunks
    retire per launch via the scanned step (0 = auto-picked from the
    measured floor/produce/compute roofline), the accumulator init is
    folded into the first block's scan and the centroid update into the
    last block's (static first/last flags), and the all-ones weight
    stack for full blocks is a cached device-resident constant, not a
    per-block put."""
    import jax

    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_streamed

    if device is None:
        device = jax.devices()[0]
    return kmeans_fit_streamed(path, centroids, iters=iters,
                               chunk_rows=chunk_rows, device=device,
                               precision=precision, timings=timings,
                               on_iter=on_iter,
                               pipeline_depth=pipeline_depth, obs=obs,
                               dispatch_batch=dispatch_batch)


def write_centroids(path: str, centroids: np.ndarray) -> None:
    """Atomic centroid writer shared by the single-process driver and the
    distributed runner.  Writes to the EXACT configured path
    (``np.save(str)`` would append '.npy'), temp + rename like every
    other writer."""
    import os

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.save(f, np.asarray(centroids, np.float32))
    os.replace(tmp, path)


def make_kmeans(centroids: np.ndarray):
    """(mapper, reducer) pair for the streamed k-means workload."""
    return KMeansMapper(centroids), SumReducer()
