"""Inverted-index build — BASELINE.json config #4 (no reference
implementation exists; the reference's only workload is word count,
/root/reference/src/main.rs:94-101).

Semantics defined here:

* a **document** is one line of the corpus;
* its **doc id** is the absolute byte offset of its first byte — unique,
  monotone in document order, and computable per chunk without a global
  line counter (chunks are newline-aligned, so every chunk starts a doc);
* the index maps each term (tokenized exactly like word count: whitespace
  split + lowercase, main.rs:96-97) to the ascending list of ids of the
  documents that contain it at least once.

This is the variable-length-value reduce word count cannot express: the
combine is list concatenation, handled by runtime/collect.CollectEngine
(collect all (term, doc) pairs, ONE device sort, segment boundaries on the
host).  The map side emits one pair per distinct term per document — the
native path (moxt_map_docs) reuses the epoch-table trick for the per-doc
distinct set.
"""

from __future__ import annotations

from collections.abc import Mapping

import numpy as np

from map_oxidize_tpu.api import Mapper, MapOutput
from map_oxidize_tpu.ops.hashing import HashDictionary, moxt64_bytes, split_u64
from map_oxidize_tpu.workloads.wordcount import tokenize


class InvertedIndexMapper(Mapper):
    """(chunk bytes, base byte offset) -> one (term-hash, doc-id) row per
    distinct term per document.  Values are the doc id's uint32 planes."""

    value_shape = (2,)
    value_dtype = np.uint32
    keys_have_dictionary = True

    def __init__(self, tokenizer: str = "ascii", use_native: bool = True):
        self.tokenizer = tokenizer
        self._native = None
        if use_native and tokenizer == "ascii":
            from map_oxidize_tpu.native import bindings

            self._native = bindings.stream_or_none(ngram=1)

    def map_docs(self, chunk, base_doc: int = 0) -> MapOutput:
        if self._native is not None:
            return self._native.map_docs(chunk, base_doc)
        return self._map_docs_python(chunk, base_doc)

    def iter_file_docs(self, path: str, chunk_bytes: int,
                       start_offset: int = 0):
        """Native mmap fast path yielding ``(MapOutput, next_offset)``, or
        None (driver falls back to the splitter + map_docs with host-tracked
        offsets)."""
        if self._native is None:
            return None
        return self._native.iter_file_docs(path, chunk_bytes, start_offset)

    def map_chunk(self, chunk) -> MapOutput:  # Mapper ABC
        raise NotImplementedError(
            "InvertedIndexMapper needs the chunk's base byte offset for doc "
            "identity — use map_docs(chunk, base_doc) or the "
            "run_inverted_index_job driver, not the offset-less map path")

    def _map_docs_python(self, chunk, base_doc: int) -> MapOutput:
        chunk = bytes(chunk)
        d = HashDictionary()
        hashes: list[int] = []
        docs: list[int] = []
        n_tokens = 0
        off = 0
        for line in chunk.split(b"\n"):
            toks = tokenize(line, self.tokenizer)
            n_tokens += len(toks)
            seen = set()
            for t in toks:
                if t not in seen:
                    seen.add(t)
                    h = moxt64_bytes(t)
                    d.add(h, t)
                    hashes.append(h)
                    docs.append(base_doc + off)
            off += len(line) + 1
        h64 = np.array(hashes, np.uint64)
        hi, lo = split_u64(h64)
        du = np.array(docs, np.uint64)
        vals = np.empty((len(docs), 2), np.uint32)
        vals[:, 0] = (du >> np.uint64(32)).astype(np.uint32)
        vals[:, 1] = (du & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        return MapOutput(hi=hi, lo=lo, values=vals, dictionary=d,
                         records_in=n_tokens)


def inverted_index_model(path: str) -> dict[bytes, list[int]]:
    """Pure-host oracle: {term: ascending doc-id list}, doc id = line start
    byte offset.  Independent of every engine and mapper under test."""
    index: dict[bytes, set[int]] = {}
    off = 0
    with open(path, "rb") as f:
        for line in f:
            for t in tokenize(line):
                index.setdefault(t, set()).add(off)
            off += len(line)
    return {t: sorted(s) for t, s in index.items()}


class Postings(Mapping):
    """CSR view over the engine's sorted (key, doc) columns: distinct term
    hashes + segment offsets + the shared doc column — the index itself, in
    the columnar form the device produced it.

    A 256MB corpus yields tens of millions of (term, doc) pairs; turning
    them into a dict of Python int lists costs GBs of boxed objects and
    seconds of loop time that most consumers (metrics, doc-frequency top-k,
    the streaming writer) never need.  Like the driver's LazyCounts, this
    Mapping answers everything it can from the arrays and materializes
    per-term lists only on access.
    """

    def __init__(self, terms: np.ndarray, offsets: np.ndarray,
                 docs: np.ndarray, dictionary: HashDictionary):
        #: distinct term hashes.  Sorted within each shard's block but NOT
        #: globally ascending: the sharded engine concatenates its
        #: hash-partitions shard-major, so lookups go through a lazy
        #: hash->row dict, never a binary search.
        self._terms = terms
        #: segment offsets: term i's docs are docs[off[i]:off[i+1]]
        self._offsets = offsets
        self._docs = docs
        self._dict = dictionary
        self._index: dict[int, int] | None = None

    @classmethod
    def from_sorted(cls, keys_sorted: np.ndarray, docs: np.ndarray,
                    dictionary: HashDictionary) -> "Postings":
        """Key-sorted (key, doc) rows -> CSR by boundary detection."""
        bounds = np.flatnonzero(
            np.concatenate([[True], keys_sorted[1:] != keys_sorted[:-1]])
        ) if keys_sorted.shape[0] else np.empty(0, np.int64)
        return cls(keys_sorted[bounds],
                   np.append(bounds, keys_sorted.shape[0]), docs, dictionary)

    # --- array-answerable queries -----------------------------------------

    def __len__(self) -> int:
        return int(self._terms.shape[0])

    @property
    def n_pairs(self) -> int:
        return int(self._docs.shape[0])

    def doc_freqs(self) -> np.ndarray:
        """Per-term document frequency, vectorized (terms in hash order)."""
        return np.diff(self._offsets)

    def top_by_df(self, k: int) -> list[tuple[bytes, int]]:
        """Top-k terms by document frequency (df desc, term asc tie-break);
        strings materialize only for the <= k winners plus boundary ties."""
        from map_oxidize_tpu.ops.topk import top_k_candidate_indices

        if len(self) == 0:
            return []
        df = self.doc_freqs()
        cand = top_k_candidate_indices(df, k)
        lookup = self._dict.lookup
        pairs = [(lookup(int(h)), int(c))
                 for h, c in zip(self._terms[cand].tolist(),
                                 df[cand].tolist())]
        pairs.sort(key=lambda kv: (-kv[1], kv[0]))
        return pairs[:k]

    # --- Mapping protocol (per-term materialization) ----------------------

    def _segment(self, i: int) -> list[int]:
        a, b = int(self._offsets[i]), int(self._offsets[i + 1])
        return self._docs[a:b].tolist()

    def __getitem__(self, term: bytes) -> list[int]:
        if self._index is None:
            self._index = {h: i for i, h in enumerate(self._terms.tolist())}
        try:
            i = self._index[moxt64_bytes(term)]
        except KeyError:
            raise KeyError(term) from None
        return self._segment(i)

    def __iter__(self):
        lookup = self._dict.lookup
        for h in self._terms.tolist():
            yield lookup(h)

    def items(self):
        """Re-iterable lazy view (NOT a one-shot generator: the Mapping
        contract allows iterating the same view twice, e.g. a report pass
        after a write pass).  Each iteration materializes one term's doc
        list at a time."""
        return _PostingsItems(self)

    def __eq__(self, other):
        if isinstance(other, Postings):
            other = dict(other.items())
        if not isinstance(other, dict):
            return NotImplemented
        return len(self) == len(other) and all(
            t in other and other[t] == d for t, d in self.items()
        )

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq


class _PostingsItems:
    """Lazy, re-iterable (term, doc-list) view over a :class:`Postings`."""

    __slots__ = ("_p",)

    def __init__(self, postings: Postings):
        self._p = postings

    def __len__(self) -> int:
        return len(self._p)

    def __iter__(self):
        p = self._p
        lookup = p._dict.lookup
        for i, h in enumerate(p._terms.tolist()):
            yield lookup(h), p._segment(i)


def postings_from_sorted(keys: np.ndarray, docs: np.ndarray,
                         dictionary: HashDictionary) -> Postings:
    """Sorted (key, doc) rows -> :class:`Postings`.  Boundary detection is a
    vectorized diff, no per-row Python.  (term, doc) pairs are unique by
    construction: the mapper emits each term once per doc and docs never
    straddle chunks — newline-aligned cuts guarantee it."""
    return Postings.from_sorted(keys, docs, dictionary)


def make_inverted_index(tokenizer: str = "ascii", use_native: bool = True):
    return InvertedIndexMapper(tokenizer, use_native)
