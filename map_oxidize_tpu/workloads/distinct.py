"""Approximate distinct-token count (HyperLogLog) — a capability the
201-line reference could not express (its only aggregate is the mutex-merged
exact count map, ``/root/reference/src/main.rs:111-150``), included to show
the Mapper/Reducer monoid boundary generalizes past ``sum``: the whole
workload is the **max monoid over a tiny integer key space**, which is the
single most TPU-friendly reduce shape this framework has —

    map:    token -> (bucket = top-p hash bits, rank = leading-zero count
            of the remaining bits + 1), pre-combined per chunk into at most
            ``m = 2^p`` register rows
    reduce: per-bucket max (device segment-max over a fixed 2^p-key
            accumulator: no growth, one executable, one tiny readback)
    emit:   harmonic-mean estimator over the m registers (host, O(m))

Token hashing reuses the word-count tokenizer stack verbatim: the native
hash-only scan (``NativeStream.iter_file_hashes`` — raw ``moxt64`` token
hashes, no tables, no strings) or the Python tokenize+hash fallback, so
ascii/unicode semantics and parity guarantees are inherited rather than
re-implemented.  Register extraction is fully vectorized: a ``bincount``
over ``bucket*64 + rank`` (ranks <= 64-p+1 < 64) and a per-row max — no
Python per token — with a bounded-scratch ``np.maximum.at`` fold above
p=16, where the bincount scratch would reach 64 * 2^p * 8B (~134MB).

Standard HLL estimator (Flajolet et al.): ``alpha_m * m^2 / sum(2^-M_j)``
with linear-counting small-range correction; relative standard error is
``1.04 / sqrt(m)`` (~0.8% at the default p=14).  64-bit hashes make the
classic large-range correction unnecessary.
"""

from __future__ import annotations

import numpy as np

from map_oxidize_tpu.api import Mapper, MapOutput, MaxReducer

#: allowed precision range, shared with config.validate: below 11 the
#: frexp-exactness argument in hll_registers needs 64-p <= 53; above 18
#: the estimator error (~0.2%) is already far below corpus-level noise.
HLL_P_MIN, HLL_P_MAX = 11, 18


def hll_registers(hashes: np.ndarray, p: int) -> np.ndarray:
    """Dense ``(2^p,)`` int32 register array from raw u64 token hashes:
    register j = max rank among hashes whose top-p bits equal j (0 when
    the bucket is empty)."""
    m = 1 << p
    if hashes.size == 0:
        return np.zeros(m, np.int32)
    hashes = np.asarray(hashes, np.uint64)
    buckets = (hashes >> np.uint64(64 - p)).astype(np.int64)
    w = (hashes & np.uint64((1 << (64 - p)) - 1)).astype(np.float64)
    # 64-p <= 60 bits... but exact float64 only to 2^53: for p >= 11 the
    # remainder fits 53 bits and frexp is exact.  frexp exponent is
    # floor(log2(w)) + 1 for w > 0, so rank = (64-p) + 1 - exponent.
    _, exp = np.frexp(w)
    ranks = np.where(w == 0, 64 - p + 1, 64 - p + 1 - exp).astype(np.int64)
    if p > 16:
        # bincount scratch is 64 * 2^p * 8B (134MB at p=18, per concurrent
        # chunk): bound it with the slower in-place fold instead
        regs = np.zeros(m, np.int32)
        np.maximum.at(regs, buckets, ranks.astype(np.int32))
        return regs
    present = np.bincount(buckets * 64 + ranks,
                          minlength=m * 64).reshape(m, 64) > 0
    return (present * np.arange(64, dtype=np.int32)).max(axis=1)


def hll_estimate(registers: np.ndarray) -> float:
    """Harmonic-mean cardinality estimate with the linear-counting
    small-range correction."""
    regs = np.asarray(registers, np.float64)
    m = regs.shape[0]
    alpha = 0.7213 / (1 + 1.079 / m)
    est = alpha * m * m / np.sum(np.exp2(-regs))
    if est <= 2.5 * m:
        zeros = int(np.count_nonzero(regs == 0))
        if zeros:
            est = m * np.log(m / zeros)
    return float(est)


class DistinctMapper(Mapper):
    """Chunk bytes -> at most ``2^p`` (bucket, max-rank) register rows.

    ``keys_have_dictionary = False``: buckets are small integers (hi = 0,
    lo = bucket), the same integer-key convention k-means uses — no host
    dictionary, no string readback.
    """

    value_shape = ()
    value_dtype = np.int32
    keys_have_dictionary = False

    def __init__(self, tokenizer: str = "ascii", use_native: bool = True,
                 p: int = 14):
        if not HLL_P_MIN <= p <= HLL_P_MAX:
            raise ValueError(
                f"hll precision must be in [{HLL_P_MIN}, {HLL_P_MAX}], "
                f"got {p}")
        self.tokenizer = tokenizer
        self.p = p
        self._native = None
        if use_native:
            from map_oxidize_tpu.native import bindings

            self._native = bindings.stream_or_none(ngram=1,
                                                   tokenizer=tokenizer)

    def _registers_output(self, regs: np.ndarray, n_tokens: int) -> MapOutput:
        """Dense ``(2^p,)`` registers (int32 or uint8) -> sparse MapOutput
        of live (bucket, max-rank) rows."""
        live = np.flatnonzero(regs)
        return MapOutput(hi=np.zeros(live.shape[0], np.uint32),
                         lo=live.astype(np.uint32),
                         values=regs[live].astype(np.int32, copy=False),
                         records_in=n_tokens)

    def map_chunk(self, chunk: bytes) -> MapOutput:
        if self._native is not None:
            regs, n_tokens = self._native.map_chunk_hll(chunk, self.p)
            return self._registers_output(regs, n_tokens)
        from map_oxidize_tpu.ops.hashing import moxt64_bytes
        from map_oxidize_tpu.workloads.wordcount import tokenize

        toks = tokenize(chunk, self.tokenizer)
        hashes = np.fromiter((moxt64_bytes(t) for t in toks),
                             np.uint64, count=len(toks))
        return self._registers_output(hll_registers(hashes, self.p),
                                      len(toks))

    def map_file(self, path: str, chunk_bytes: int, start_offset: int = 0):
        """Native mmap fast path: the C++ scan max-folds (bucket, rank)
        into the ``2^p`` registers in-loop — no hash buffer, no host-side
        extraction (the round-4 NumPy bincount held distinct to ~170 MB/s
        against the 544-589 MB/s hash-only scan)."""
        if self._native is None:
            return None

        def _iter():
            for regs, n_tokens, off in self._native.iter_file_hll(
                    path, chunk_bytes, self.p, start_offset):
                yield self._registers_output(regs, n_tokens), off

        return _iter()


def distinct_model(chunks, tokenizer: str = "ascii") -> int:
    """Exact oracle: distinct lowercased tokens across all chunks (the
    number HLL approximates), reference tokenize semantics."""
    from map_oxidize_tpu.workloads.wordcount import tokenize

    seen = set()
    for chunk in chunks:
        seen.update(tokenize(chunk, tokenizer))
    return len(seen)


def write_distinct_output(path: str, regs: np.ndarray, estimate: float,
                          p: int) -> None:
    """Atomic distinct-result writer, shared by the single-process driver
    and the distributed runner (registers max-merge exactly, so both write
    byte-identical files).  ``.npy``: the raw registers — the mergeable
    artifact (np.maximum of two runs' registers estimates the union).
    Anything else: a deterministic text summary."""
    import os

    tmp = f"{path}.tmp.{os.getpid()}"
    if path.endswith(".npy"):
        with open(tmp, "wb") as f:
            np.save(f, regs)
    else:
        with open(tmp, "w") as f:
            f.write(f"estimate\t{estimate:.1f}\n"
                    f"precision\t{p}\n"
                    f"registers_filled\t{int(np.count_nonzero(regs))}\n")
    os.replace(tmp, path)


def make_distinct(tokenizer: str = "ascii", use_native: bool = True,
                  p: int = 14):
    return DistinctMapper(tokenizer, use_native, p), MaxReducer()
