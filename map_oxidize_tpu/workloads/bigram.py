"""Bigram count — compound string keys over a wider key space
(BASELINE.json config #3; no reference implementation exists, so semantics are
defined here: adjacent token pairs *within a chunk's token stream*, key string
``"tok1 tok2"``).

This exists to stress exactly what word count doesn't: key cardinality (order
|V|^2 rather than |V|) and longer key bytes.  The device path is unchanged —
compound keys are just another 64-bit hash — which is the point of the
Mapper/Reducer boundary.

Note on chunking: pairs that straddle a chunk boundary are not counted, and
results are therefore a function of the chunking (documented, deterministic
for a given config).  The parity model in tests uses the same chunking.
"""

from __future__ import annotations

from collections import Counter

import numpy as np

from map_oxidize_tpu.api import Mapper, MapOutput, SumReducer
from map_oxidize_tpu.ops.hashing import HashDictionary, moxt64_bytes, split_u64
from map_oxidize_tpu.workloads.wordcount import tokenize


class RescanDictionary(HashDictionary):
    """Strings-on-demand dictionary for the hash-only map path.

    In hash-only mode the map emits raw n-gram hashes and NO key bytes — the
    millions of distinct pair strings a wide-key corpus carries are exactly
    what made the map loop DRAM-bound and the per-chunk dictionary drain the
    finalize tax.  But strings are only ever needed for the <= top-k winners
    (plus boundary ties) or a requested full text output, and every counted
    key occurs in the corpus: ONE extra native scan with the same chunk cuts
    recovers the bytes for any queried hash set (and byte-compares repeat
    occurrences, so collisions involving surfaced keys are still detected).

    ``prefetch(hashes)`` resolves what is not yet known; consumers that need
    strings (LazyCounts.top_k, materialization) call it with exactly the
    hashes they are about to look up.
    """

    __slots__ = ("_stream", "_path", "_chunk_bytes", "_early_stop")

    def __init__(self, stream, path: str, chunk_bytes: int,
                 early_stop: bool = True):
        super().__init__()
        self._stream = stream
        self._path = path
        self._chunk_bytes = chunk_bytes
        #: stop the rescan once every queried hash has been seen (top-k
        #: winners are the most frequent keys, so this typically ends within
        #: the first chunks); config.rescan_full=True forces the whole-corpus
        #: scan, which extends the collision byte-check to every occurrence
        self._early_stop = early_stop

    def prefetch(self, hashes) -> None:
        hashes = np.asarray(hashes, np.uint64)
        if hashes.size == 0:
            return
        known = self.materialized()
        if known:
            missing = hashes[[int(h) not in known for h in hashes.tolist()]] \
                if hashes.size <= 64 else \
                hashes[~np.isin(hashes,
                                np.fromiter(known.keys(), np.uint64,
                                            count=len(known)))]
        else:
            missing = hashes
        if missing.size == 0:
            return
        h, lens, blob = self._stream.resolve_file(
            self._path, self._chunk_bytes, np.unique(missing),
            early_stop=self._early_stop)
        self.add_arrays(h, lens, blob)
        self._flush()

    def lookup(self, h: int) -> bytes:
        try:
            return super().lookup(h)
        except KeyError:
            self.prefetch(np.array([h], np.uint64))
            return super().lookup(h)


class BigramMapper(Mapper):
    value_shape = ()
    value_dtype = np.int32
    keys_have_dictionary = True
    wide_keys = True  # distinct pairs ~ |V|^2: collect-reduce territory

    def __init__(self, tokenizer: str = "ascii", use_native: bool = True):
        self.tokenizer = tokenizer
        self._native = None
        #: set by the driver when the engine is the host collect-reduce:
        #: map emits raw hashes only; strings resolve by rescan on demand
        self.hash_only = False
        if use_native:
            from map_oxidize_tpu.native import bindings

            self._native = bindings.stream_or_none(ngram=2,
                                                   tokenizer=tokenizer)

    @property
    def supports_hash_only(self) -> bool:
        return self._native is not None

    def rescan_dictionary(self, path: str, chunk_bytes: int,
                          early_stop: bool = True) -> RescanDictionary:
        return RescanDictionary(self._native, path, chunk_bytes, early_stop)

    def map_file(self, path: str, chunk_bytes: int, start_offset: int = 0):
        """Native mmap fast path (see WordCountMapper.map_file)."""
        if self._native is None:
            return None
        if self.hash_only:
            return self._native.iter_file_hashes(path, chunk_bytes,
                                                 start_offset)
        return self._native.iter_file(path, chunk_bytes, start_offset)

    def map_chunk(self, chunk: bytes) -> MapOutput:
        if self._native is not None:
            return self._native.map_chunk(chunk)
        toks = tokenize(chunk, self.tokenizer)
        pairs = Counter(
            toks[i] + b" " + toks[i + 1] for i in range(len(toks) - 1)
        )
        d = HashDictionary()
        hashes = np.empty(len(pairs), np.uint64)
        values = np.empty(len(pairs), np.int32)
        for i, (key, c) in enumerate(pairs.items()):
            h = moxt64_bytes(key)
            d.add(h, key)
            hashes[i] = h
            values[i] = c
        hi, lo = split_u64(hashes)
        return MapOutput(hi=hi, lo=lo, values=values, dictionary=d,
                         records_in=max(len(toks) - 1, 0))


def make_bigram(tokenizer: str = "ascii", use_native: bool = True):
    return BigramMapper(tokenizer, use_native), SumReducer()
