#!/usr/bin/env python
"""Headline benchmark: end-to-end word-count throughput (words/sec/chip).

Prints ONE compact JSON line as the FINAL stdout line:
``{"metric", "value", "unit", "vs_baseline", "headline_corpus_mb",
"workloads": {name: vs_baseline}, "detail_file"}`` — small enough to
survive a tail-capture harness.  The full per-size/per-phase detail goes
to ``.bench_cache/BENCH_DETAIL.json`` (round 3's artifact was unparseable
precisely because that detail was inlined into the stdout line).

``vs_baseline`` is the speedup over the measured CPU reference baseline — a
single-threaded host run of the reference program's exact semantics
(tokenize per ``/root/reference/src/main.rs:94-101``, merge per
main.rs:131-134; see ``workloads/reference_model.py``).  The reference
publishes no numbers and its corpus was stripped (SURVEY.md §6), so the
baseline is measured here, on this machine, on the same corpus — and top-k
parity between the two runs is asserted, so the speedup is apples-to-apples.

Corpus: deterministic synthetic Zipf text (seeded), cached under
``.bench_cache/``.  Size via ``MOXT_BENCH_MB`` (default 64; the baseline is
timed on a capped slice and rate-extrapolated since single-thread Python is
O(minutes) at 10x that size).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

CACHE_DIR = os.path.join(REPO, ".bench_cache")
# persist XLA executables across runs/rounds so compile time never pollutes
# a measured run (first-ever compiles happen in the warm run regardless)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(CACHE_DIR, "xla_cache"))
#: sizes to run, comma-separated MB; the LAST is the headline metric.
#: The sweep ends at 10240MB — the BASELINE.json-defined north-star config —
#: so the driver-captured headline is the 10GB number, not a smaller proxy.
#: Corpora generate once and stay cached across rounds.
BENCH_SIZES = [int(s) for s in
               os.environ.get("MOXT_BENCH_MB", "64,256,1024,10240").split(",")]
BASELINE_CAP_MB = int(os.environ.get("MOXT_BENCH_BASELINE_CAP_MB", "8"))
#: measured runs per size (best is reported; the tunnel jitters ~±150 ms)
RUNS = int(os.environ.get("MOXT_BENCH_RUNS", "3"))
#: also time the secondary workloads (bigram, inverted index, k-means)
BENCH_WORKLOADS = os.environ.get("MOXT_BENCH_WORKLOADS", "1") == "1"
TOP_K = 10


def make_corpus(path: str, target_mb: int) -> None:
    """Deterministic Zipf corpus: 30k-word vocab (mixed case + punctuation
    variants so the lowercase/no-strip semantics matter), ~12 words/line."""
    rng = np.random.default_rng(1234)
    v = 30_000
    alphabet = np.frombuffer(b"abcdefghijklmnopqrstuvwxyz", np.uint8)
    lengths = rng.integers(2, 11, size=v)
    vocab = []
    for i, L in enumerate(lengths):
        w = bytes(rng.choice(alphabet, size=int(L)).tobytes())
        r = i % 10
        if r == 7:
            w = w.capitalize()          # exercises lowercasing
        elif r == 8:
            w = w + b","                # punctuation kept, distinct key
        elif r == 9:
            w = w + b"."
        vocab.append(w)
    vocab = np.array(vocab, dtype=object)
    # Zipf-ish rank weights (s=1.1), the realistic word-frequency shape
    ranks = np.arange(1, v + 1, dtype=np.float64)
    p = ranks ** -1.1
    p /= p.sum()

    target = target_mb * 1024 * 1024
    tmp = path + ".tmp"
    written = 0
    with open(tmp, "wb") as f:
        while written < target:
            toks = rng.choice(vocab, size=1_000_000, p=p)
            lines = []
            for i in range(0, 1_000_000, 12):
                lines.append(b" ".join(toks[i:i + 12]))
            blob = b"\n".join(lines) + b"\n"
            f.write(blob)
            written += len(blob)
    os.replace(tmp, path)


def make_realtext_corpus(path: str, target_mb: int) -> None:
    """Real English text (BASELINE names shakes.txt/enwik9; the build
    environment has zero egress, so the source is the public-domain and
    permissively-licensed English prose shipped in the image: license
    texts, third-party notices, package METADATA descriptions, stdlib
    .rst docs).  The ~15-20MB deterministic base is tiled to the target
    size — tiling preserves the natural token-length/punctuation
    distribution and vocabulary that the synthetic Zipf corpus lacks
    (its fixed 27,561-key space was round 3's 'tame' critique)."""
    import glob

    pats = [
        "/opt/venv/lib/python3.12/site-packages/**/LICENSE*",
        "/opt/venv/lib/python3.12/site-packages/**/*NOTICES*.txt",
        "/opt/venv/lib/python3.12/site-packages/**/METADATA",
        "/usr/lib/python3*/**/*.rst",
        "/usr/share/common-licenses/*",
        "/usr/share/doc/*/copyright",
    ]
    files = sorted({f for p in pats for f in glob.glob(p, recursive=True)
                    if os.path.isfile(f) and os.path.getsize(f) > 3000})
    base = []
    base_bytes = 0
    for f in files:
        try:
            with open(f, "rb") as fh:
                raw = fh.read()
        except OSError:
            continue
        # keep prose-like files: mostly printable ASCII (drops the CJK
        # dictionary files and binary-ish blobs some packages ship)
        a = np.frombuffer(raw, np.uint8)
        if a.size == 0:
            continue
        printable = int((((a >= 32) & (a < 127)) | (a == 10)).sum())
        if printable >= 0.97 * a.size:
            base.append(raw.rstrip(b"\n"))
            base_bytes += len(raw) + 1
        if base_bytes > 24 * 1024 * 1024:
            break
    if not base:
        # no prose-like files in this image: fail loudly rather than tile
        # a b"\n" blob into a zero-token corpus (the baseline would then
        # divide by zero; advisor r4)
        raise RuntimeError(
            "make_realtext_corpus found no prose-like files under the "
            "image glob paths; skip the realtext bench entry on this host")
    blob = b"\n".join(base) + b"\n"
    target = target_mb * 1024 * 1024
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        written = 0
        while written < target:
            f.write(blob)
            written += len(blob)
    os.replace(tmp, path)


def make_unique_corpus(path: str, target_mb: int) -> int:
    """Near-unique token stream: every token is the 12-hex-digit encoding
    of a random 48-bit draw, so the distinct count ~= the token count
    (the handful of birthday collisions is counted exactly below) and an
    exact in-RAM set at this scale would cost GBs while HLL registers
    stay at 2^p * 4 bytes.  Returns the EXACT distinct count (ground
    truth from the generator) and writes it to a sidecar json."""
    meta_path = path + ".meta.json"
    if os.path.isfile(path) and os.path.isfile(meta_path):
        with open(meta_path) as f:
            return json.load(f)["distinct"]
    rng = np.random.default_rng(99)
    target = target_mb * 1024 * 1024
    per_tok = 13  # 12 hex chars + 1 separator
    n = target // per_tok
    draws = rng.integers(0, 1 << 48, n, dtype=np.uint64)
    distinct = int(np.unique(draws).shape[0])
    hexmap = np.frombuffer(b"0123456789abcdef", np.uint8)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        step = 4_000_000
        for s in range(0, n, step):
            d = draws[s:s + step]
            m = d.shape[0]
            out = np.empty((m, per_tok), np.uint8)
            for j in range(12):  # hex digit j = bits (44 - 4j)..
                out[:, j] = hexmap[((d >> np.uint64(44 - 4 * j))
                                    & np.uint64(0xF)).astype(np.int64)]
            out[:, 12] = ord(" ")
            out[11::12, 12] = ord("\n")  # ~12 tokens per line
            f.write(out.tobytes())
    os.replace(tmp, path)
    with open(meta_path, "w") as f:
        json.dump({"distinct": distinct, "tokens": int(n)}, f)
    return distinct


def _run_size(run_job, JobConfig, corpus: str, warm: bool):
    """One corpus size: optional warm run (XLA compile + transfer-shape
    warmup), then RUNS measured runs; returns (best JobResult, best seconds,
    per-run seconds)."""
    if warm:
        run_job(JobConfig(input_path=corpus, output_path="", backend="auto",
                          metrics=False), "wordcount")
    best = None
    times = []
    for _ in range(max(RUNS, 1)):
        cfg = JobConfig(
            input_path=corpus,
            output_path=os.path.join(CACHE_DIR, "final_result.txt"),
            backend="auto",
            top_k=TOP_K,
            metrics=True,
        )
        t0 = time.perf_counter()
        result = run_job(cfg, "wordcount")
        dt = time.perf_counter() - t0
        times.append(dt)
        if best is None or dt < best[1]:
            best = (result, dt)
    return best[0], best[1], times


def parse_args(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="map_oxidize_tpu headline benchmark (see module "
                    "docstring); sizes/runs via MOXT_BENCH_* env vars")
    ap.add_argument("--ledger-dir", default=os.environ.get(
        "MOXT_BENCH_LEDGER_DIR"),
        help="append one normalized entry per benchmarked workload to "
             "<dir>/ledger.jsonl (the obs run-ledger format)")
    ap.add_argument("--gate", action="store_true",
                    help="exit 3 when any workload's rate regressed beyond "
                         "the tolerance vs its previous ledger entry "
                         "(default ledger: .bench_cache/ledger)")
    ap.add_argument("--gate-tolerance-pct", type=float, default=float(
        os.environ.get("MOXT_BENCH_GATE_TOL_PCT", "10")),
        help="regression tolerance percent for --gate (default 10)")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.gate and not args.ledger_dir:
        args.ledger_dir = os.path.join(CACHE_DIR, "ledger")
    # Keep stdout/stderr quiet so the final JSON line is the only thing a
    # tail capture needs: silence jax's WARNING-level chatter (donation
    # warnings alone were a multi-KB wall in round 3) and Python warnings.
    logging.disable(logging.WARNING)
    import warnings
    warnings.simplefilter("ignore")
    os.makedirs(CACHE_DIR, exist_ok=True)

    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime import run_job
    from map_oxidize_tpu.workloads.reference_model import top_k_model, wordcount_model

    # --- session probes (round-4 verdict #5): the artifact must
    # self-describe its session so a reader can normalize across the
    # host's ±15% single-thread drift and the 50-1200 MB/s link variance
    # (benchmarks/RESULTS.md) without re-running anything.
    probes = _session_probes()
    # export the MEASURED matmul peak for the xprof MFU join: every job
    # this process runs from here on quotes achieved FLOP/s against the
    # session's sustained rate, not a nominal spec sheet number
    if probes.get("matmul_peak_bf16_tflops"):
        os.environ.setdefault(
            "MOXT_PEAK_FLOPS",
            str(probes["matmul_peak_bf16_tflops"] * 1e12))

    # --- CPU reference baseline: single-thread, reference semantics
    # (tokenize per main.rs:94-101, merge per main.rs:131-134), measured on a
    # capped slice and rate-extrapolated (it's O(n))
    base_corpus = os.path.join(CACHE_DIR, f"zipf_{BENCH_SIZES[0]}mb.txt")
    if not os.path.isfile(base_corpus):
        make_corpus(base_corpus, BENCH_SIZES[0])
    cap = BASELINE_CAP_MB * 1024 * 1024
    with open(base_corpus, "rb") as f:
        slice_bytes = f.read(cap)
    slice_bytes = slice_bytes[: slice_bytes.rfind(b"\n") + 1]
    # best-of-2: the HEADLINE ratio divides by this one number, and the
    # ±15% host drift (benchmarks/RESULTS.md) on a single reading moves
    # every row of the artifact; a second 8MB pass costs ~9s
    base_s = None
    for _ in range(2):
        t0 = time.perf_counter()
        base_counts = wordcount_model([slice_bytes])
        dt = time.perf_counter() - t0
        base_s = dt if base_s is None else min(base_s, dt)
    base_rate = sum(base_counts.values()) / base_s

    # --- parity gate: our top-k on the slice must equal the model's
    tmp_slice = os.path.join(CACHE_DIR, "slice.txt")
    with open(tmp_slice, "wb") as f:
        f.write(slice_bytes)
    slice_res = run_job(
        JobConfig(input_path=tmp_slice, output_path="", backend="auto",
                  metrics=False, top_k=TOP_K), "wordcount")
    if slice_res.top[:TOP_K] != top_k_model(base_counts, TOP_K):
        print(json.dumps({"error": "top-k parity FAILED vs reference model"}))
        return 1

    # --- secondary workloads FIRST: the 10GB sweep's process state (peak
    # heap, page-cache churn) measurably taxed them when they ran after it
    # (II 256MB: 5.25s post-sweep vs 3.0s fresh); the sweep itself streams
    # and is insensitive to ordering
    workloads = {}
    if BENCH_WORKLOADS:
        workloads = _bench_workloads(run_job, JobConfig, probes)
        _release_heap()

    # --- per-size sweep; the LAST size is the headline
    per_size = []
    headline = None
    headline_pairs = None
    for mb in BENCH_SIZES:
        corpus = os.path.join(CACHE_DIR, f"zipf_{mb}mb.txt")
        if not os.path.isfile(corpus):
            make_corpus(corpus, mb)
        if mb == BENCH_SIZES[-1]:
            # HEADLINE: alternate baseline and framework phases, 3 pairs,
            # and cite the MEDIAN per-pair ratio (round-4 verdict #5: the
            # numerator was stable across rounds while a single up-front
            # baseline reading swung the artifact's every row by ±39%;
            # same-session A/B is the discipline bigram already follows)
            slice_words = sum(base_counts.values())
            fw_cfg = JobConfig(
                input_path=corpus,
                output_path=os.path.join(CACHE_DIR, "final_result.txt"),
                backend="auto", top_k=TOP_K, metrics=True)
            run_job(JobConfig(input_path=corpus, output_path="",
                              backend="auto", metrics=False),
                    "wordcount")  # warm: compile + transfer shapes
            pairs = []
            result = None
            for _ in range(3):
                _release_heap()
                t0 = time.perf_counter()
                wordcount_model([slice_bytes])
                b_rate = slice_words / (time.perf_counter() - t0)
                _release_heap()  # the model's ~2M boxed objects tax GC
                t0 = time.perf_counter()
                result = run_job(fw_cfg, "wordcount")
                secs = time.perf_counter() - t0
                words = result.metrics["records_in"]
                pairs.append({
                    "cpu_baseline_words_per_sec": round(b_rate, 1),
                    "words_per_sec": round(words / secs, 1),
                    "ratio": round(words / secs / b_rate, 3),
                })
            ratios = sorted(p["ratio"] for p in pairs)
            rates = sorted(p["words_per_sec"] for p in pairs)
            med_ratio, med_rate = ratios[1], rates[1]
            headline = (med_rate, words, med_ratio)
            headline_pairs = pairs
            per_size.append({
                "corpus_mb": mb,
                "words": int(words),
                "median_words_per_sec": round(med_rate, 1),
                "vs_baseline_median_of_pairs": med_ratio,
                "pairs": pairs,
                "distinct_keys": int(result.metrics["distinct_keys"]),
                "phases": {k: round(v, 4)
                           for k, v in result.metrics.items()
                           if k.startswith("time/")},
                "metrics_snapshot": _metrics_snapshot(result),
            })
            continue
        result, secs, times = _run_size(run_job, JobConfig, corpus, warm=True)
        words = result.metrics["records_in"]
        rate = words / secs
        per_size.append({
            "corpus_mb": mb,
            "words": int(words),
            "best_s": round(secs, 3),
            "runs_s": [round(t, 3) for t in times],
            "words_per_sec": round(rate, 1),
            "vs_baseline": round(rate / base_rate, 3),
            "distinct_keys": int(result.metrics["distinct_keys"]),
            "phases": {k: round(v, 4) for k, v in result.metrics.items()
                       if k.startswith("time/")},
            "metrics_snapshot": _metrics_snapshot(result),
        })
        headline = (rate, words, rate / base_rate)

    # SLO plane summary: any alert fired during a benchmarked run rides
    # the artifact (and fails --gate below — a clean bench must be
    # alert-silent; the rules already encode the tolerance)
    alert_summary: dict = {"fired": 0, "by_workload": {}}
    for _name, _e in workloads.items():
        if isinstance(_e, dict):
            _f = (_e.get("metrics_snapshot") or {}).get("alerts/fired")
            if isinstance(_f, (int, float)) and _f > 0:
                alert_summary["fired"] += _f
                alert_summary["by_workload"][_name] = _f

    detail_path = os.path.join(CACHE_DIR, "BENCH_DETAIL.json")
    with open(detail_path, "w") as f:
        json.dump({
            "metric": "wordcount_words_per_sec_per_chip",
            "value": round(headline[0], 1),
            "unit": "words/sec",
            "vs_baseline": round(headline[2], 3),
            "headline_corpus_mb": BENCH_SIZES[-1],
            "headline_method": "median of 3 alternating baseline/framework "
                               "pairs" if headline_pairs else "best-of-runs "
                               "vs up-front baseline",
            "cpu_baseline_words_per_sec": round(base_rate, 1),
            "session_probes": probes,
            "alert_summary": alert_summary,
            "per_size": per_size,
            "workloads": workloads,
        }, f, indent=1)

    # --- run ledger + regression gate: every benchmarked workload appends
    # one normalized entry (rate + vs_baseline), and --gate compares each
    # against its PREVIOUS entry before appending — the machine-checked
    # regression story connecting BENCH rounds
    gate_failures: list[str] = []
    if args.ledger_dir:
        from map_oxidize_tpu.obs import ledger as _ledger

        for entry in _bench_ledger_entries(headline, workloads):
            if args.gate:
                gate_failures += [
                    f"{entry['workload']}: {r}"
                    for r in _ledger.gate_against_previous(
                        args.ledger_dir, entry, args.gate_tolerance_pct)]
                # the SLO plane's absolute gate: ANY alert firing on a
                # clean benchmarked run fails, prior entry or not (the
                # cross-run alerts/fired diff only catches increases)
                fired = entry["metrics"].get("alerts/fired")
                if isinstance(fired, (int, float)) and fired > 0:
                    gate_failures.append(
                        f"{entry['workload']}: {fired:g} SLO alert(s) "
                        "fired during the benchmarked run")
            _ledger.append(args.ledger_dir, entry)

    # compact scoreboard line: one ratio per workload, full detail on disk.
    # scoreboard=False entries (shapes the decomposition proves unwinnable,
    # kept as labeled records) stay in the detail file only.
    wl_ratios = {}
    for name, entry in workloads.items():
        if isinstance(entry, dict) and "vs_baseline" in entry:
            if entry.get("scoreboard", True):
                wl_ratios[name] = entry["vs_baseline"]
        elif name.endswith("_error"):
            wl_ratios[name] = entry  # surface gate failures, compactly
    sys.stdout.flush()
    print(json.dumps({
        "metric": "wordcount_words_per_sec_per_chip",
        "value": round(headline[0], 1),
        "unit": "words/sec",
        "vs_baseline": round(headline[2], 3),
        "headline_corpus_mb": BENCH_SIZES[-1],
        "workloads": wl_ratios,
        "detail_file": os.path.relpath(detail_path, REPO),
    }))
    if gate_failures:
        # stderr so the stdout tail-capture contract (final line = the
        # JSON scoreboard) survives a failing gate
        for f in gate_failures:
            print(f"GATE REGRESSION: {f}", file=sys.stderr)
        return 3
    return 0


def _bench_ledger_entries(headline, workloads) -> list:
    """Normalize the bench results into obs-ledger entries: one per
    workload under the ``bench/`` namespace, rates under the common
    ``rate`` key the ledger's regression diff understands.  The config
    hash is the bench harness version — sizes/workload configs are fixed
    by the script, so same-hash entries compare apples-to-apples."""
    import time as _time

    from map_oxidize_tpu import __version__

    now = round(_time.time(), 3)
    base = {"ts_unix_s": now, "version": __version__,
            "config_hash": "bench-harness-v1", "n_processes": 1,
            "phases_s": {}}
    entries = [dict(base, workload="bench/wordcount_headline",
                    corpus_bytes=BENCH_SIZES[-1] << 20,
                    metrics={"rate": round(headline[0], 1),
                             "vs_baseline": round(headline[2], 3)})]
    rate_keys = ("words_per_sec", "tokens_per_sec", "point_iters_per_sec",
                 "median_words_per_sec", "median_tokens_per_sec",
                 "rows_per_sec")
    for name, e in sorted(workloads.items()):
        if not isinstance(e, dict):
            continue
        rate = next((e[k] for k in rate_keys if k in e), None)
        if rate is None:
            continue
        metrics = {"rate": rate, "vs_baseline": e.get("vs_baseline")}
        # XLA-, comms-, and spill-layer gate fields ride along: a
        # recompile, an MFU drop, unexplained comms-bytes growth,
        # unexplained spill growth, or a stall episode in a benchmarked
        # workload fails --gate exactly like a rate drop (comms bytes
        # and spill volumes are deterministic accounting identities, so
        # same-config entries compare exactly)
        metrics.update({k: v for k, v in e.get("metrics_snapshot",
                                               {}).items()
                        if k.startswith(("compile/", "xprof/", "comms/",
                                         "heartbeat/", "alerts/",
                                         "spill/", "demote/",
                                         "shuffle/transport"))})
        entry = dict(base, workload=f"bench/{name}", metrics=metrics)
        if "ab_pairs" in e:
            # these entries switched measurement method (best-of ->
            # alternating-pairs median) in round 6; a distinct hash makes
            # the ledger gate refuse the apples-to-oranges comparison
            # against pre-change entries instead of flagging a phantom
            # regression (a median reads systematically below a best-of)
            entry["config_hash"] = "bench-harness-v2-pairs"
        entries.append(entry)
    return entries


def _session_probes() -> dict:
    """Fixed-work host and link probes, recorded in the artifact so a
    reader can normalize ratios across sessions: the build host's
    single-thread rate drifts ~±15% and the host->device link has been
    measured anywhere from 26 MB/s to 1.2 GB/s for the same put
    (benchmarks/RESULTS.md link-variance note)."""
    probes: dict = {}
    # host probe: a fixed pure-Python workload (~0.2s nominal) — the same
    # interpreter work class as the reference-model baseline
    t0 = time.perf_counter()
    acc = 0
    for i in range(2_000_000):
        acc += i * i
    probes["host_spin_s"] = round(time.perf_counter() - t0, 4)
    probes["host_spin_work"] = "sum(i*i, i<2e6)"
    # link probe: one 128MB device_put, fetch-forced
    try:
        import jax

        mb = 128
        buf = np.ones(mb << 20, np.uint8)
        dev = jax.devices()[0]
        jax.device_put(buf[:1 << 20], dev).block_until_ready()  # wake link
        t0 = time.perf_counter()
        jax.device_put(buf, dev).block_until_ready()
        dt = time.perf_counter() - t0
        probes["link_put_mb"] = mb
        probes["link_put_s"] = round(dt, 4)
        probes["link_put_mb_per_s"] = round(mb / dt, 1)
        probes["device"] = str(dev.platform)
        del buf
    except Exception as e:  # cpu-only or tunnel-down hosts still bench
        probes["link_probe_error"] = str(e)
    # matmul-peak probes: the ACHIEVABLE MXU rate on this part.  Round-5
    # measurement: this chip sustains ~91 TFLOP/s bf16 and ~18 TFLOP/s
    # f32(HIGHEST) on large square matmuls — about half the v5e nominal
    # 197e12 — so an MFU quoted only against the nominal peak understates
    # occupancy ~2x.  The kmeans entries report both.
    try:
        import jax
        import jax.numpy as jnp
        from jax import lax

        if jax.devices()[0].platform == "cpu":
            # ~25 TFLOP of probe matmuls would grind for minutes on the
            # 1-core host and record meaningless "peaks"
            probes["matmul_probe_skipped"] = "cpu-only host"
            return probes
        rng = np.random.default_rng(0)
        # bf16 needs the larger shape to saturate (4096^3 reads ~8x low —
        # launch-bound); f32-HIGHEST saturates at 4096^3 already
        for name, m, f in (
                ("bf16", 8192, lambda a, b: jnp.dot(
                    a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32)),
                ("f32_highest", 4096, lambda a, b: jnp.dot(
                    a, b, precision=lax.Precision.HIGHEST))):
            a = jax.device_put(rng.normal(size=(m, m)).astype(np.float32))
            b = jax.device_put(rng.normal(size=(m, m)).astype(np.float32))
            reps = 10
            # hoist-proof AND pipelineable: each rep's operand differs by
            # a one-element scatter (LICM cannot treat the dot as
            # loop-invariant), but reps carry no matmul->matmul data
            # dependency, so they overlap like real back-to-back work.  A
            # carry-chained form measured ~2x LOW (dependent HIGHEST
            # passes cannot pipeline — it read below what the kmeans loop
            # itself achieves); a fully invariant body risks reading
            # reps x HIGH if hoisted.
            g = jax.jit(lambda a, b, f=f: lax.fori_loop(
                0, reps,
                lambda i, acc: acc + f(
                    a.at[0, 0].set(i.astype(jnp.float32)), b)[0, 0],
                jnp.float32(0.0)))
            np.asarray(g(a, b))  # compile + warm
            t0 = time.perf_counter()
            np.asarray(g(a, b))
            dt = (time.perf_counter() - t0) / reps
            probes[f"matmul_peak_{name}_tflops"] = round(
                2.0 * m ** 3 / dt / 1e12, 1)
            del a, b
    except Exception as e:
        probes["matmul_probe_error"] = str(e)
    return probes


def _metrics_snapshot(result) -> dict:
    """Per-workload observability snapshot for BENCH_DETAIL.json: phase
    wall-clocks, spill/demotion/shuffle volume counters, peak RSS,
    feed/flush latency quantiles, and the streaming-pipeline overlap
    evidence (``pipeline/feed_wait_ms`` / ``pipeline/overlap_ratio`` —
    how much host map time hid behind device dispatch) from the job's
    obs registry — so a future BENCH_r*.json delta can be decomposed by
    phase instead of re-run archaeology."""
    m = getattr(result, "metrics", None) or {}
    snap = {k: v for k, v in m.items()
            if k.startswith(("time/", "spill/", "demote/", "checkpoint/",
                             "shuffle/", "engine/", "mem/", "pipeline/",
                             "feed_block_ms/", "compile/", "xprof/",
                             "device/", "hbm/", "comms/", "heartbeat/",
                             "dispatch/", "alerts/", "attrib/",
                             "profile/", "calib/", "critpath/",
                             "plan/"))}
    return snap


def _alternating_pairs(baseline_fn, base_units, framework_fn, fw_units,
                       unit: str, n_pairs: int = 3):
    """The headline's robustness method (median of alternating baseline/
    framework pairs — see the headline block in ``main``) applied to a
    secondary workload entry: baseline and framework re-measure
    back-to-back inside each pair, so the ±15% session host drift hits
    BOTH sides of each ratio instead of one up-front baseline reading
    swinging the whole row (VERDICT r5 weak #1: realtext read 4.96x —
    under the 5x bar — from exactly that).

    ``base_units`` is the fixed baseline work size (slice tokens/words);
    ``fw_units(result)`` extracts the framework run's numerator.
    Returns ``(last_framework_result, entry_fields)`` where the entry
    carries the per-pair readings and the median rate/ratio under
    ``median_<unit>`` / ``vs_baseline``."""
    pairs = []
    result = None
    secs_list = []
    for _ in range(n_pairs):
        _release_heap()
        t0 = time.perf_counter()
        baseline_fn()
        b_rate = base_units / (time.perf_counter() - t0)
        _release_heap()
        t0 = time.perf_counter()
        result = framework_fn()
        secs = time.perf_counter() - t0
        f_rate = fw_units(result) / secs
        secs_list.append(round(secs, 3))
        pairs.append({
            f"cpu_baseline_{unit}": round(b_rate, 1),
            unit: round(f_rate, 1),
            "ratio": round(f_rate / b_rate, 3),
        })
    ratios = sorted(p["ratio"] for p in pairs)
    rates = sorted(p[unit] for p in pairs)
    entry = {
        "runs_s": secs_list,
        f"median_{unit}": rates[len(rates) // 2],
        "vs_baseline": ratios[len(ratios) // 2],
        "method": f"median of {n_pairs} alternating baseline/framework "
                  "pairs",
        "ab_pairs": pairs,
    }
    return result, entry


def _release_heap() -> None:
    """Return freed arena pages to the kernel between bench phases so one
    phase's peak heap doesn't tax the next phase's allocations (measured:
    ~0.3s on the 256MB inverted-index entry after a 1GB wordcount run)."""
    import ctypes
    import gc

    gc.collect()
    try:
        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except OSError:
        pass  # non-glibc: harmless to skip


def _bench_workloads(run_job, JobConfig, probes=None) -> dict:
    """Secondary workload benches (BASELINE configs 3-5): bigram and
    inverted index run at a real size (default 256MB) against a measured
    single-thread CPU baseline of the same semantics, with top-k/postings
    parity asserted on the baseline slice — each entry carries its own
    ``vs_baseline`` ratio, mirroring the word-count headline's method."""
    import numpy as np

    out = {}

    def best_of(fn, n=2):
        best_r, best_t = None, None
        for _ in range(n):
            t0 = time.perf_counter()
            r = fn()
            dt = time.perf_counter() - t0
            if best_t is None or dt < best_t:
                best_r, best_t = r, dt  # result stays paired with ITS time
        return best_r, best_t

    wl_mb = int(os.environ.get("MOXT_BENCH_WORKLOAD_MB", "256"))
    corpus = os.path.join(CACHE_DIR, f"zipf_{wl_mb}mb.txt")
    if not os.path.isfile(corpus):
        make_corpus(corpus, wl_mb)
    slice_path = os.path.join(CACHE_DIR, "slice.txt")
    with open(slice_path, "rb") as f:
        slice_bytes = f.read()

    # --- bigram (config #3: key cardinality ~|V|^2, longer key bytes)
    from collections import Counter

    from map_oxidize_tpu.workloads.reference_model import top_k_model
    from map_oxidize_tpu.workloads.wordcount import tokenize

    # best-of-2 on the BASELINE too: the ±15% session drift
    # (benchmarks/RESULTS.md) hits both sides of the ratio, and a one-shot
    # baseline reading that lands slow inflates every bigram ratio
    def _bigram_baseline():
        toks = tokenize(slice_bytes)
        return toks, Counter(toks[i] + b" " + toks[i + 1]
                             for i in range(len(toks) - 1))

    (toks, bigram_base), bigram_base_s = best_of(_bigram_baseline, n=2)
    bigram_base_rate = max(len(toks) - 1, 1) / bigram_base_s
    # parity gate on the slice (one chunk there, so model chunking matches).
    # num_shards=1: bigram auto-routes to the host collect-reduce engine,
    # which needs no device — pinning the shard count skips TPU client init
    # (~15-60 s through the tunnel) that the job would never use.
    slice_cfg = JobConfig(input_path=slice_path, output_path="",
                          backend="auto", metrics=False, top_k=TOP_K,
                          num_shards=1)
    # gate-failure convention (every gate below): record `<wl>_error`, skip
    # only THAT workload's timed entry, and keep measuring the rest — one
    # bad estimator or parity regression must not discard unrelated rows
    sr = run_job(slice_cfg, "bigram")
    bigram_ok = sr.top[:TOP_K] == top_k_model(bigram_base, TOP_K)
    if not bigram_ok:
        out["bigram_error"] = "bigram top-k parity FAILED vs host model"
    # the timed regions below must not drag the parity gates' object heaps
    # (~2M live Python objects between the token list, the bigram Counter,
    # and later the postings model): generational GC pauses scale with the
    # live set, and measured the II entry ~1s slower with them resident
    n_toks = len(toks)
    del toks, bigram_base, sr
    _release_heap()

    if bigram_ok:
        cfg = JobConfig(input_path=corpus, output_path="", backend="auto",
                        metrics=True, key_capacity=1 << 25, num_shards=1)
        run_job(cfg, "bigram")  # warm
        r, secs = best_of(lambda: run_job(cfg, "bigram"), n=3)
        rate = r.metrics["records_in"] / secs
        out[f"bigram_{wl_mb}mb"] = {
            "best_s": round(secs, 3),
            "words_per_sec": round(rate, 1),
            "vs_baseline": round(rate / bigram_base_rate, 3),
            "cpu_baseline_words_per_sec": round(bigram_base_rate, 1),
            "distinct_keys": int(r.metrics["distinct_keys"]),
            "metrics_snapshot": _metrics_snapshot(r),
        }

    # --- inverted index (config #4: variable-length values)
    _release_heap()
    from map_oxidize_tpu.workloads.inverted_index import inverted_index_model

    # best-of-2 on the baseline, same rationale as bigram's: this entry's
    # ratio moved 6.9x -> 11.7x between the two round-5 runs almost
    # entirely on one slow one-shot baseline reading
    ii_model = inverted_index_model(slice_path)  # parity gate input
    sr = run_job(slice_cfg, "invertedindex")
    ii_slice_records = sr.metrics["records_in"]  # same tokenize => same count
    ii_ok = sr.postings == ii_model
    if not ii_ok:
        out["invertedindex_error"] = \
            "inverted-index parity FAILED vs host model"
    del ii_model, sr  # ~1M boxed ints of postings model: see bigram note
    _release_heap()

    if ii_ok:
        # alternating-pairs median (VERDICT r5 #1): the round-5 artifact's
        # two full runs moved this entry 6.9x -> 11.7x almost entirely on
        # one slow one-shot baseline reading — pair-local baselines kill
        # that failure mode the same way they did for the headline
        cfg = JobConfig(input_path=corpus, output_path="", backend="auto",
                        metrics=True, num_shards=1)
        run_job(cfg, "invertedindex")  # warm
        r, entry = _alternating_pairs(
            lambda: inverted_index_model(slice_path), ii_slice_records,
            lambda: run_job(cfg, "invertedindex"),
            lambda res: res.metrics["records_in"],
            "tokens_per_sec")
        entry.update({
            "pairs": int(r.metrics["pairs"]),
            "distinct_terms": int(r.metrics["distinct_terms"]),
            "metrics_snapshot": _metrics_snapshot(r),
        })
        out[f"invertedindex_{wl_mb}mb"] = entry

    # --- distinct (beyond-reference): HyperLogLog approximate cardinality.
    # Baseline = single-thread EXACT distinct (Python set over reference-
    # semantics tokens).  Approximate-vs-exact is the workload's point —
    # the entry reports the estimate error alongside the speedup, and a
    # slice-level accuracy gate (<3.3% = 4 sigma at p=14) must pass first.
    _release_heap()
    from map_oxidize_tpu.workloads.distinct import distinct_model

    t0 = time.perf_counter()
    exact_slice = distinct_model([slice_bytes])
    d_base_rate = n_toks / (time.perf_counter() - t0)
    sr = run_job(JobConfig(input_path=slice_path, output_path="",
                           backend="auto", metrics=False), "distinct")
    if abs(sr.estimate - exact_slice) / exact_slice > 0.033:
        out["distinct_error"] = "distinct estimate accuracy gate FAILED"
    else:
        cfg = JobConfig(input_path=corpus, output_path="", backend="auto",
                        metrics=True)
        run_job(cfg, "distinct")  # warm
        r, secs = best_of(lambda: run_job(cfg, "distinct"))
        rate = r.metrics["records_in"] / secs
        out[f"distinct_{wl_mb}mb"] = {
            "best_s": round(secs, 3),
            "tokens_per_sec": round(rate, 1),
            "vs_baseline": round(rate / d_base_rate, 3),
            "cpu_baseline_tokens_per_sec": round(d_base_rate, 1),
            "estimate": round(r.estimate, 1),
            "slice_error_pct": round(
                100 * abs(sr.estimate - exact_slice) / exact_slice, 2),
            "metrics_snapshot": _metrics_snapshot(r),
        }

    # --- wordcount on REAL text (BASELINE's shakes.txt/enwik9 intent):
    # natural token-length/punctuation distributions and vocabulary, own
    # same-session baseline — the synthetic Zipf rows all share one tame
    # 27,561-key space (round-3 weak #7)
    _release_heap()
    from map_oxidize_tpu.workloads.reference_model import wordcount_model

    rt_corpus = os.path.join(CACHE_DIR, "realtext_256mb.txt")
    rt_ok = True
    if not os.path.isfile(rt_corpus):
        try:
            make_realtext_corpus(rt_corpus, 256)
        except RuntimeError as e:  # no prose sources in this image
            out["wordcount_realtext_error"] = str(e)
            rt_ok = False
    if rt_ok:
        with open(rt_corpus, "rb") as f:
            rt_slice = f.read(8 * 1024 * 1024)
        rt_slice = rt_slice[: rt_slice.rfind(b"\n") + 1]
        rt_slice_path = os.path.join(CACHE_DIR, "realtext_slice.txt")
        with open(rt_slice_path, "wb") as f:
            f.write(rt_slice)
        rt_counts = wordcount_model([rt_slice])  # parity gate input
        rt_slice_words = sum(rt_counts.values())
        sr = run_job(JobConfig(input_path=rt_slice_path, output_path="",
                               backend="auto", metrics=False, top_k=TOP_K,
                               num_shards=1), "wordcount")
        rt_ok = (rt_slice_words > 0
                 and sr.top[:TOP_K] == top_k_model(rt_counts, TOP_K))
        if not rt_ok:
            # rt_slice_words == 0 means a degenerate corpus (text sources
            # missing on this host) — skip the entry, keep measuring the
            # rest
            out["wordcount_realtext_error"] = (
                "real-text corpus degenerate (no text sources found)"
                if rt_slice_words <= 0
                else "real-text top-k parity FAILED vs reference model")
        del rt_counts, sr  # parity-model heap must not tax later timed runs
    if rt_ok:
        # alternating-pairs median (VERDICT r5 #1): this entry read 4.96x
        # in the round-5 citable artifact — under the 5x bar — while its
        # RESULTS.md re-runs read 6.63x/3.86x on baseline swing alone;
        # pair-local baselines are the proven fix
        _release_heap()
        cfg = JobConfig(input_path=rt_corpus, output_path="",
                        backend="auto", metrics=True, num_shards=1)
        run_job(cfg, "wordcount")  # warm
        r, entry = _alternating_pairs(
            lambda: wordcount_model([rt_slice]), rt_slice_words,
            lambda: run_job(cfg, "wordcount"),
            lambda res: res.metrics["records_in"],
            "words_per_sec")
        entry.update({
            "distinct_keys": int(r.metrics["distinct_keys"]),
            "metrics_snapshot": _metrics_snapshot(r),
        })
        out["wordcount_realtext_256mb"] = entry

    # --- distinct(HLL) where exactness is infeasible (round-3 weak #5):
    # ~82M near-unique tokens at 1GB.  An exact set would hold ~82M
    # 12-byte keys (Python set: ~7GB; even a bare u64 hash set: ~1.3GB);
    # the HLL registers stay at 2^p * 4 bytes.  Ground truth comes from
    # the generator (exact distinct of the 48-bit draws), so the entry
    # reports true estimate error at a scale no in-RAM set could check.
    _release_heap()
    uq_mb = int(os.environ.get("MOXT_BENCH_UNIQUE_MB", "1024"))
    uq_corpus = os.path.join(CACHE_DIR, f"unique_{uq_mb}mb.txt")
    uq_true = make_unique_corpus(uq_corpus, uq_mb)
    # same-session exact-set baseline, on a capped slice (exactness is
    # the thing that does not scale — that is the point), rate-extrapolated
    from map_oxidize_tpu.workloads.wordcount import tokenize as _tok

    with open(uq_corpus, "rb") as f:
        uq_slice = f.read(8 * 1024 * 1024)
    uq_slice = uq_slice[: uq_slice.rfind(b"\n") + 1]
    t0 = time.perf_counter()
    uq_toks = _tok(uq_slice)
    uq_set = set(uq_toks)
    uq_base_s = time.perf_counter() - t0
    uq_base_rate = len(uq_toks) / uq_base_s
    # measured exact-set memory on the slice, extrapolated to the corpus
    set_bytes = sys.getsizeof(uq_set) + sum(
        sys.getsizeof(t) for t in list(uq_set)[:10000]) / 10000 * len(uq_set)
    exact_est_bytes = set_bytes * (uq_true / max(len(uq_set), 1))
    del uq_toks, uq_set
    _release_heap()
    cfg = JobConfig(input_path=uq_corpus, output_path="", backend="auto",
                    metrics=True)
    run_job(cfg, "distinct")  # warm
    r, secs = best_of(lambda: run_job(cfg, "distinct"))
    rate = r.metrics["records_in"] / secs
    p_bits = int(np.log2(r.registers.shape[0]))
    out[f"distinct_unique_{uq_mb}mb"] = {
        "best_s": round(secs, 3),
        "tokens_per_sec": round(rate, 1),
        "vs_baseline": round(rate / uq_base_rate, 3),
        "cpu_baseline_tokens_per_sec": round(uq_base_rate, 1),
        "estimate": round(r.estimate, 1),
        "true_distinct": uq_true,
        "error_pct": round(100 * abs(r.estimate - uq_true) / uq_true, 3),
        "hll_registers_bytes": int(r.registers.shape[0] * 4),
        "exact_set_bytes_est": int(exact_est_bytes),
        "hll_p": p_bits,
    }

    # k-means: dense vector values (config #5).  Center-seeded from
    # round 8 (pts[:64] = the true centers, the 4M corpus's convention):
    # the streamed-DEVICE formulation now measured here reassociates
    # float sums differently from NumPy, and on an unseeded corpus a
    # couple of assignment-boundary ties land either side of rtol 1e-3
    # without being wrong — seeding conditions the parity gate.  New
    # cache filename so stale unseeded corpora regenerate; the ratio is
    # same-session vs the same corpus, so rounds stay comparable.
    _release_heap()
    pts_path = os.path.join(CACHE_DIR, "kmeans_points_cs.npy")
    if not os.path.isfile(pts_path):
        rng = np.random.default_rng(42)
        c = rng.normal(0, 10, (64, 32)).astype(np.float32)
        pts = (c[rng.integers(0, 64, 400_000)]
               + rng.normal(0, 0.5, (400_000, 32))).astype(np.float32)
        pts[:64] = c
        np.save(pts_path, pts)

    # CPU baseline: single-thread NumPy of the same semantics — the SAME
    # vectorized formulation the host mapper uses (argmin-distance assign,
    # bincount partial sums), not the per-cluster-mask oracle, so the ratio
    # measures the framework against a competent host implementation.
    from map_oxidize_tpu.workloads.kmeans import assign_points

    def km_cpu_iter(p, c):
        cid = assign_points(p, c)
        k, d = c.shape
        sums = np.empty((k, d), np.float32)
        for j in range(d):
            sums[:, j] = np.bincount(cid, weights=p[:, j], minlength=k)
        counts = np.bincount(cid, minlength=k)
        new = c.copy()
        nz = counts > 0
        new[nz] = sums[nz] / counts[nz, None]
        return new

    pts_all = np.asarray(np.load(pts_path, mmap_mode="r"), np.float32)
    km_init = pts_all[:64].copy()
    t0 = time.perf_counter()
    km_base = km_init
    for _ in range(2):
        km_base = km_cpu_iter(pts_all, km_base)
    km_base_rate = pts_all.shape[0] * 2 / (time.perf_counter() - t0)

    # streamed (mapper='native' pins the streaming path; 'auto' now
    # resolves to the device fit for in-memory points) vs the HBM-resident
    # device variant (points transfer once, iterations are MXU matmuls
    # that amortize it).  EACH variant is parity-gated on its own 2-iter
    # run vs 2 baseline iterations; a failing variant records its error
    # and is skipped without discarding the other (gate-failure
    # convention above).
    from map_oxidize_tpu.runtime.dispatch import (
        dispatch_floor_snapshot,
        measured_dispatch_floor_ms,
    )

    # the r01-r05 formulation (host-assign engine stream, mapper=native)
    # rides along as a continuity field: the row's trajectory across
    # rounds stays decomposable into "formulation change" vs "same-path
    # speedup"
    cfg = JobConfig(input_path=pts_path, output_path="", backend="auto",
                    metrics=True, kmeans_k=64, kmeans_iters=2,
                    mapper="native")
    r = run_job(cfg, "kmeans")  # warm + parity gate (2 iters == 2 baseline)
    if not np.allclose(r.centroids, km_base, rtol=1e-3, atol=1e-3):
        out["kmeans_stream_error"] = "kmeans parity FAILED vs NumPy baseline"
    else:
        r, secs = best_of(lambda: run_job(cfg, "kmeans"))
        host_assign_ratio = r.metrics["records_in"] / secs / km_base_rate
        # the streaming regime's winning formulation at 400k since the
        # scan-batched dispatch work (ISSUE 8 / ROADMAP open item 3):
        # stream THROUGH the device in ~52k-row chunks (--chunk-mb 32 is
        # honored now that batching owns launch amortization), dispatch
        # batch auto-resolved from the measured floor/produce/compute
        # roofline.  Round-5's "no streaming formulation can win at this
        # shape" verdict was a statement about one-chunk-per-launch
        # schedules — scan-batching retires B chunks per launch, so the
        # row is promoted to the scoreboard the moment it crosses 1x.
        cfg_sd = JobConfig(input_path=pts_path, output_path="",
                           backend="auto", metrics=True, kmeans_k=64,
                           kmeans_iters=2, mapper="auto",
                           kmeans_device_fit_bytes=64,  # pin stream_device
                           chunk_bytes=32 << 20, dispatch_batch=0)
        # floor window: this entry's own dispatches only — the ledger is
        # process-global and the 4M entry below reuses the same program,
        # so an unwindowed mean would cross-contaminate the two rows'
        # trajectory records
        floor_since = dispatch_floor_snapshot("kmeans/stream_step")
        r_sd = run_job(cfg_sd, "kmeans")  # warm + parity gate
        if not np.allclose(r_sd.centroids, km_base, rtol=1e-3, atol=1e-3):
            out["kmeans_stream_error"] = (
                "streamed-device 400k parity FAILED vs NumPy baseline")
            # the continuity field still rides: a regression that breaks
            # only the stream_device formulation must not also erase the
            # r01-r05 host-assign trajectory record — the decomposition
            # into "formulation change" vs "same-path speedup" is the
            # reason the field exists
            out["kmeans_streamed_400k_d32_k64"] = {
                "scoreboard": False,
                "cpu_baseline_point_iters_per_sec": round(km_base_rate, 1),
                "host_assign_vs_baseline": round(host_assign_ratio, 3),
                "note": "streamed-device parity failed this round (see "
                        "kmeans_stream_error); host-assign continuity "
                        "field only",
            }
        else:
            r_sd, secs = best_of(lambda: run_job(cfg_sd, "kmeans"))
            rate = r_sd.metrics["records_in"] / secs
            ratio = rate / km_base_rate
            floor = measured_dispatch_floor_ms("kmeans/stream_step",
                                               since=floor_since)
            out["kmeans_streamed_400k_d32_k64"] = {
                "best_s": round(secs, 3),
                "point_iters_per_sec": round(rate, 1),
                "vs_baseline": round(ratio, 3),
                "cpu_baseline_point_iters_per_sec": round(km_base_rate, 1),
                "iters": int(r_sd.metrics["iters"]),
                # promoted once the streaming regime beats the CPU
                # baseline at this shape (ISSUE 8 satellite); below 1x
                # it stays a labeled detail record
                "scoreboard": bool(ratio >= 1.0),
                "formulation": "scan-batched stream_device, 32MB chunks",
                "dispatch_batch": r_sd.metrics.get("dispatch/batch"),
                "dispatch_batch_mode": r_sd.metrics.get(
                    "dispatch/batch_mode"),
                # measured per-launch host overhead of the streamed step
                # (mean steady-state dispatch gap): THE dispatch-floor
                # trajectory record this row exists to track per round
                "dispatch_floor_ms": (round(floor, 4)
                                      if floor is not None else None),
                "host_assign_vs_baseline": round(host_assign_ratio, 3),
                "metrics_snapshot": _metrics_snapshot(r_sd),
                "note": "streamed-through-device with scan-batched "
                        "dispatch (B logical chunks per launch); "
                        "host_assign_vs_baseline tracks the r01-r05 "
                        "engine-stream formulation on the same corpus",
            }

    # --- k-means, DEVICE-streamed at the scale the streaming regime is
    # about (round-5, verdict r4 #5): 4M x 32 points (512MB f32) stream
    # through the chip in 2M-row chunks, one dispatch per chunk (the
    # measured ~200ms/launch tunnel cost is the binding constraint, not
    # the link — RESULTS.md round 5), centroid update folded into the
    # last chunk's step.  bf16 mode halves the link bytes and is the
    # headline; f32 rides as a field.  Same-session NumPy baseline; f32
    # parity gate vs 2 baseline iterations (center-seeded corpus).
    _release_heap()
    from map_oxidize_tpu.workloads.kmeans import kmeans_fit_streamed_device

    n4, d4 = 4_000_000, 32
    pts4_path = os.path.join(CACHE_DIR, "kmeans_points_4m_d32.npy")
    if not os.path.isfile(pts4_path):
        rng = np.random.default_rng(17)
        c4 = rng.normal(0, 10, (64, d4)).astype(np.float32)
        tmp = pts4_path + ".tmp.npy"
        pts4 = (c4[rng.integers(0, 64, n4)]
                + rng.normal(0, 0.5, (n4, d4)).astype(np.float32))
        pts4[:64] = c4  # center-seeded: parity holds at rtol 1e-3
        np.save(tmp, pts4)
        os.replace(tmp, pts4_path)
        del pts4, c4
        _release_heap()
    pts4 = np.asarray(np.load(pts4_path, mmap_mode="r"), np.float32)
    km4_init = pts4[:64].copy()
    t0 = time.perf_counter()
    km4_base = km4_init
    for _ in range(2):
        km4_base = km_cpu_iter(pts4, km4_base)
    km4_base_rate = n4 * 2 / (time.perf_counter() - t0)
    del pts4
    _release_heap()
    # scan-batched from round 8: 512k-row chunks, 8 chunks retired per
    # launch (one scanned program per iteration).  B is PINNED, not
    # auto: auto's roofline models the per-launch host floor, but the
    # measured win here also includes XLA fusing/scheduling the whole
    # scanned iteration as one executable — a benefit the floor model
    # does not see, so the bench pins the swept optimum and records it.
    # Both precisions measured; the entry's headline is the faster one
    # (bf16 halves link bytes and wins where transfers bind — TPU; f32
    # wins where bf16 matmuls emulate and the cast costs — CPU).
    cr4, b4 = 512 << 10, 8
    floor4_since = dispatch_floor_snapshot("kmeans/stream_step")
    sd_f32 = kmeans_fit_streamed_device(pts4_path, km4_init, iters=2,
                                        chunk_rows=cr4,
                                        dispatch_batch=b4)  # warm + gate
    if not np.allclose(sd_f32, km4_base, rtol=1e-3, atol=1e-3):
        out["kmeans_streamed_device_error"] = \
            "streamed-device parity FAILED vs NumPy baseline"
    else:
        _, secs_f32 = best_of(lambda: kmeans_fit_streamed_device(
            pts4_path, km4_init, iters=2, chunk_rows=cr4,
            dispatch_batch=b4))
        f32_rate = n4 * 2 / secs_f32
        kmeans_fit_streamed_device(pts4_path, km4_init, iters=2,
                                   chunk_rows=cr4, dispatch_batch=b4,
                                   precision="bf16")  # warm bf16 program
        _, secs_b16 = best_of(lambda: kmeans_fit_streamed_device(
            pts4_path, km4_init, iters=2, chunk_rows=cr4,
            dispatch_batch=b4, precision="bf16"))
        b16_rate = n4 * 2 / secs_b16
        best_prec = "bf16" if b16_rate >= f32_rate else "f32"
        rate_sd, secs_sd = ((b16_rate, secs_b16)
                            if best_prec == "bf16"
                            else (f32_rate, secs_f32))
        floor = measured_dispatch_floor_ms("kmeans/stream_step",
                                           since=floor4_since)
        out["kmeans_streamed_device_4m_d32_k64"] = {
            "best_s": round(secs_sd, 3),
            "point_iters_per_sec": round(rate_sd, 1),
            "vs_baseline": round(rate_sd / km4_base_rate, 3),
            "cpu_baseline_point_iters_per_sec": round(km4_base_rate, 1),
            "f32_vs_baseline": round(f32_rate / km4_base_rate, 3),
            "bf16_vs_baseline": round(b16_rate / km4_base_rate, 3),
            "precision": f"{best_prec} stream (f32 parity-gated; "
                         "headline = faster precision)",
            "chunk_rows": cr4,
            "dispatch_batch": b4,
            "dispatch_floor_ms": (round(floor, 4)
                                  if floor is not None else None),
            "iters": 2,
        }

    # --- k-means, compute-bound (the MXU-dense configuration): 2M x 64
    # points, k=256, 100 HBM-resident iterations.  The 400k/k=64 config
    # above is transfer- and launch-dominated (round-3 verdict: ~0.01%
    # MFU); this one runs ~13.1 TFLOP of f32(HIGHEST) matmul per timed
    # run, so the entry reports achieved FLOP/s and MFU alongside the
    # wall-clock ratio.  FLOPs counted: distance matmul (2ndk) + one-hot
    # partial-sum matmul (2nkd) per iteration; argmin/one-hot/counts are
    # O(nk) and excluded.
    _release_heap()
    del pts_all
    n2, d2_, k2, iters2 = 2_000_000, 64, 256, 100
    pts2_path = os.path.join(CACHE_DIR, "kmeans_points_2m_d64.npy")
    if not os.path.isfile(pts2_path):
        rng = np.random.default_rng(7)
        c = rng.normal(0, 10, (k2, d2_)).astype(np.float32)
        tmp = pts2_path + ".tmp.npy"
        pts = (c[rng.integers(0, k2, n2)]
               + rng.normal(0, 0.5, (n2, d2_)).astype(np.float32))
        # first k rows = the true centers: the default init (first k
        # points) then starts from well-separated, well-populated Voronoi
        # cells, so the handful of near-tie assignment flips between the
        # f32 oracle and the HIGHEST-precision MXU matmul (~1e-5 of
        # points) moves each centroid by ~1/|cell| — parity holds at
        # rtol 1e-3.  Init from arbitrary points leaves sliver cells of
        # 2-3 points where one flipped point IS the mean.
        pts[:k2] = c
        np.save(tmp, pts)  # f32 by construction; astype would copy 512MB
        os.replace(tmp, pts2_path)
        del pts, c
        _release_heap()

    pts2 = np.asarray(np.load(pts2_path, mmap_mode="r"), np.float32)
    km2_init = pts2[:k2].copy()
    t0 = time.perf_counter()
    km2_base = km2_init
    for _ in range(2):
        km2_base = km_cpu_iter(pts2, km2_base)
    km2_base_rate = n2 * 2 / (time.perf_counter() - t0)
    del pts2
    _release_heap()

    gate_cfg = JobConfig(input_path=pts2_path, output_path="",
                         backend="auto", metrics=False, kmeans_k=k2,
                         kmeans_iters=2, mapper="device")
    r = run_job(gate_cfg, "kmeans")  # warm (compile both shapes) + gate
    if not np.allclose(r.centroids, km2_base, rtol=1e-3, atol=1e-3):
        out["kmeans_device_error"] = \
            "kmeans device parity FAILED vs NumPy baseline"
    else:
        cfg = JobConfig(input_path=pts2_path, output_path="",
                        backend="auto", metrics=True, kmeans_k=k2,
                        kmeans_iters=iters2, mapper="device")
        run_job(cfg, "kmeans")  # warm the timed iteration count
        r, secs = best_of(lambda: run_job(cfg, "kmeans"))
        rate = r.metrics["records_in"] / secs
        entry = {
            "best_s": round(secs, 3),
            "point_iters_per_sec": round(rate, 1),
            "vs_baseline": round(rate / km2_base_rate, 3),
            "cpu_baseline_point_iters_per_sec": round(km2_base_rate, 1),
            "iters": int(r.metrics["iters"]),
        }
        iter_s = r.metrics.get("time/iter_s")
        if iter_s:  # single-device path only; the sharded fit (multi-
            # device hosts) reports no phase split, and an MFU over full
            # wall time would be wrong-but-plausible — omit it instead
            flops = 4.0 * n2 * d2_ * k2 * iters2
            # peak reference: v5e MXU bf16 ~197 TFLOP/s; the matmuls run
            # f32 via Precision.HIGHEST (multi-pass bf16) for oracle
            # parity, so bf16-peak MFU understates occupancy by the pass
            # count
            peak = float(os.environ.get("MOXT_TPU_PEAK_FLOPS", 197e12))
            entry.update({
                "transfer_s": r.metrics.get("time/transfer_s"),
                "iter_s": iter_s,
                "flops_per_sec": round(flops / iter_s, 1),
                "mfu_pct": round(100 * flops / iter_s / peak, 2),
                "precision": "f32(Precision.HIGHEST)",
            })
            meas = (probes or {}).get("matmul_peak_f32_highest_tflops")
            if meas:  # vs this part's MEASURED f32 matmul rate
                pct = round(100 * flops / iter_s / (meas * 1e12), 2)
                entry["mfu_vs_measured_peak_pct"] = pct
                if pct > 100:
                    # the single-shape probe is a conservative reference:
                    # HIGHEST's multi-pass form can sustain above it at
                    # the workload's shape (observed 9.5-17.7 TFLOP/s
                    # probe spread across one afternoon)
                    entry["measured_peak_note"] = (
                        "probe is a lower-bound reference; the sustained "
                        "loop exceeded it this session")
        out[f"kmeans_device_2m_d64_k256_{iters2}iter"] = entry

        # --- bf16 variant (round-4 verdict #6): --kmeans-precision bf16
        # runs each matmul as ONE native MXU pass (f32 accumulation via
        # preferred_element_type) instead of HIGHEST's multi-pass f32
        # emulation — the only fair basis for a bf16-peak MFU figure.
        # Convergence-parity gate: the 100-iter bf16 trajectory must stay
        # within bf16 rounding of the f32-HIGHEST centroids (same bound
        # tests/test_kmeans.py pins on CPU); drift is reported either way.
        bcfg = JobConfig(input_path=pts2_path, output_path="",
                         backend="auto", metrics=True, kmeans_k=k2,
                         kmeans_iters=iters2, mapper="device",
                         kmeans_precision="bf16")
        run_job(bcfg, "kmeans")  # warm/compile the bf16 program
        rb, secs_b = best_of(lambda: run_job(bcfg, "kmeans"))
        scale = float(np.abs(r.centroids).max())
        drift = float(np.abs(rb.centroids - r.centroids).max())
        drift_ok = drift <= 4 * 2.0**-8 * scale
        rate_b = rb.metrics["records_in"] / secs_b
        entry_b = {
            "best_s": round(secs_b, 3),
            "point_iters_per_sec": round(rate_b, 1),
            "vs_baseline": round(rate_b / km2_base_rate, 3),
            "cpu_baseline_point_iters_per_sec": round(km2_base_rate, 1),
            "iters": int(rb.metrics["iters"]),
            "max_drift_vs_f32": round(drift, 5),
            "drift_bound": round(4 * 2.0**-8 * scale, 5),
            "precision": "bf16 (native MXU, f32 accumulation)",
        }
        iter_sb = rb.metrics.get("time/iter_s")
        if iter_sb:
            flops = 4.0 * n2 * d2_ * k2 * iters2
            peak = float(os.environ.get("MOXT_TPU_PEAK_FLOPS", 197e12))
            entry_b.update({
                "transfer_s": rb.metrics.get("time/transfer_s"),
                "iter_s": iter_sb,
                "flops_per_sec": round(flops / iter_sb, 1),
                "mfu_pct": round(100 * flops / iter_sb / peak, 2),
            })
            meas = (probes or {}).get("matmul_peak_bf16_tflops")
            if meas:
                entry_b["mfu_vs_measured_peak_pct"] = round(
                    100 * flops / iter_sb / (meas * 1e12), 2)
        if not drift_ok:
            out["kmeans_bf16_error"] = (
                f"bf16 drift {drift:.4f} exceeds rounding bound "
                f"{4 * 2.0**-8 * scale:.4f} vs f32-HIGHEST")
        out[f"kmeans_device_bf16_2m_d64_k256_{iters2}iter"] = entry_b

    # --- resident job service (ISSUE-7): N back-to-back small wordcounts
    # through the server — the warm-compile story, measured and gated
    _release_heap()
    try:
        entry = _bench_serve(slice_path)
    except Exception as e:  # the serve bench must not discard other rows
        out["serve_warm_small_jobs_error"] = f"{type(e).__name__}: {e}"
    else:
        if "error" in entry:
            out["serve_warm_small_jobs_error"] = entry["error"]
        else:
            out["serve_warm_small_jobs"] = entry

    # --- spilled distributed shuffle (ISSUE-10): a 2-process inverted
    # index forced past --collect-max-rows — the per-process disk
    # transport must COMPLETE with oracle parity; spill volume rides the
    # entry's metrics_snapshot, where the ledger's spill gate flags any
    # later unexplained growth
    _release_heap()
    try:
        entry = _bench_2proc_spill(slice_path)
    except Exception as e:
        out["inverted_index_2proc_spill_error"] = f"{type(e).__name__}: {e}"
    else:
        if "error" in entry:
            out["inverted_index_2proc_spill_error"] = entry["error"]
        else:
            out["inverted_index_2proc_spill"] = entry

    # --- pipelined push shuffle (ISSUE-19): the map-side combiner A-B
    # (comms bytes must drop, output byte-identical) and a skewed
    # 2-process reduce under the push transport (nonzero shuffle
    # overlap + barrier-transport parity gated); shuffle/push_* ride
    # each entry's metrics_snapshot for the ledger
    # reshard_selected_2proc (ISSUE-20): the exchange-collective A-B +
    # store-driven auto selection, byte-parity enforced, with the
    # decision and calib/* coverage gauges in metrics_snapshot
    for name, fn in (("wordcount_combined", _bench_wordcount_combined),
                     ("skewed_reduce_2proc_pipelined",
                      _bench_2proc_pipelined),
                     ("reshard_selected_2proc", _bench_reshard_selected)):
        _release_heap()
        try:
            entry = fn(slice_path)
        except Exception as e:
            out[f"{name}_error"] = f"{type(e).__name__}: {e}"
        else:
            if "error" in entry:
                out[f"{name}_error"] = entry["error"]
            else:
                out[name] = entry

    # --- dataflow workloads (ISSUE-14): total-order sort + hash
    # equi-join, oracle-parity-enforced, riding the same ledger gate
    # (comms/compile/spill fields in metrics_snapshot)
    for name, fn in (("sort", _bench_sort), ("join", _bench_join)):
        _release_heap()
        try:
            entry = fn(run_job, JobConfig)
        except Exception as e:
            out[f"{name}_error"] = f"{type(e).__name__}: {e}"
        else:
            if "error" in entry:
                out[f"{name}_error"] = entry["error"]
            else:
                out[entry.pop("entry_name")] = entry
    return out


def _bench_records(name: str, n: int, key_bits: int, seed: int) -> str:
    """Deterministic cached (u64 key, u64 payload) records corpus."""
    os.makedirs(CACHE_DIR, exist_ok=True)
    path = os.path.join(CACHE_DIR, f"{name}.npy")
    if not os.path.isfile(path):
        rng = np.random.default_rng(seed)
        keys = rng.integers(0, 1 << key_bits, n, dtype=np.uint64)
        pay = rng.integers(0, 1 << 62, n, dtype=np.uint64)
        np.save(path, np.stack([keys, pay], axis=1))
    return path


def _bench_sort(run_job, JobConfig) -> dict:
    """``sort_<n>m``: total-order sort of (u64 key, u64 payload) records
    vs the single-thread NumPy lexsort baseline (the oracle itself —
    measured, then used to enforce exact output parity).  Detail-first
    entry: on a CPU mesh the framework runs the SAME lexsort plus
    routing, so the ratio is a decomposition-bounded < 1 and stays off
    the scoreboard; the row exists for the trajectory and the gate
    (rate + comms/compile/spill snapshot)."""
    from map_oxidize_tpu.workloads.sort import read_sorted_records

    n = int(os.environ.get("MOXT_BENCH_SORT_ROWS", str(4_000_000)))
    corpus = _bench_records(f"sort_recs_{n}", n, 62, seed=101)
    recs = np.load(corpus, mmap_mode="r").view(np.uint64)
    keys = np.ascontiguousarray(recs[:, 0])
    pay = np.ascontiguousarray(recs[:, 1])
    # baseline best-of-2: one np.lexsort of the same rows
    base_s = None
    for _ in range(2):
        t0 = time.perf_counter()
        order = np.lexsort((pay, keys))
        dt = time.perf_counter() - t0
        base_s = dt if base_s is None else min(base_s, dt)
    want_k, want_p = keys[order], pay[order]
    base_rate = n / base_s
    out_path = os.path.join(CACHE_DIR, "sorted.bin")
    cfg = JobConfig(input_path=corpus, output_path=out_path,
                    backend="auto", metrics=True,
                    chunk_bytes=16 << 20, batch_size=1 << 18)
    run_job(cfg, "sort")  # warm: compile + transfer shapes
    t0 = time.perf_counter()
    r = run_job(cfg, "sort")
    secs = time.perf_counter() - t0
    got_k, got_p = read_sorted_records(out_path)
    if not (np.array_equal(got_k, want_k)
            and np.array_equal(got_p, want_p)):
        return {"error": "sort output parity FAILED vs np.lexsort oracle"}
    del got_k, got_p, want_k, want_p, order
    rate = n / secs
    return {
        "entry_name": f"sort_{n // 1_000_000}m_rows",
        "best_s": round(secs, 3),
        "rows_per_sec": round(rate, 1),
        "vs_baseline": round(rate / base_rate, 3),
        "cpu_baseline_rows_per_sec": round(base_rate, 1),
        "scoreboard": False,  # CPU-mesh sort = the baseline's lexsort
        #                       plus routing; decomposition-bounded < 1
        "note": "total-order sort, oracle-parity-enforced vs "
                "np.lexsort (detail entry; gate-watched via "
                "metrics_snapshot)",
        "metrics_snapshot": _metrics_snapshot(r),
    }


def _bench_join(run_job, JobConfig) -> dict:
    """``join_<n>m``: hash equi-join of two record corpora vs a
    single-thread vectorized NumPy sort-merge baseline of the same
    semantics, full output parity enforced."""
    from map_oxidize_tpu.workloads.join import read_join_records

    n = int(os.environ.get("MOXT_BENCH_JOIN_ROWS", str(1_000_000)))
    # ~n/4 distinct keys per side over a shared space: a few matches
    # per key, output ~O(n)
    kbits = max(int(np.log2(max(n // 4, 2))), 2)
    a_path = _bench_records(f"join_a_{n}", n, kbits, seed=102)
    b_path = _bench_records(f"join_b_{n}", n, kbits, seed=103)
    ra = np.load(a_path, mmap_mode="r").view(np.uint64)
    rb = np.load(b_path, mmap_mode="r").view(np.uint64)
    ka, pa = np.ascontiguousarray(ra[:, 0]), np.ascontiguousarray(ra[:, 1])
    kb, pb = np.ascontiguousarray(rb[:, 0]), np.ascontiguousarray(rb[:, 1])

    def _np_join():
        # independent vectorized sort-merge: sort both sides, count
        # matches per key via searchsorted, expand the cross products
        oa = np.lexsort((pa, ka))
        ob = np.lexsort((pb, kb))
        ska, spa = ka[oa], pa[oa]
        skb, spb = kb[ob], pb[ob]
        lo = np.searchsorted(skb, ska, side="left")
        hi = np.searchsorted(skb, ska, side="right")
        m = hi - lo
        tot = int(m.sum())
        seg = np.repeat(np.arange(ska.shape[0]), m)
        pos = np.arange(tot) - np.repeat(np.cumsum(m) - m, m)
        jk = ska[seg]
        ja = spa[seg]
        jb = spb[lo[seg] + pos]
        order = np.lexsort((jb, ja, jk))
        return jk[order], ja[order], jb[order]

    base_s = None
    want = None
    for _ in range(2):
        t0 = time.perf_counter()
        want = _np_join()
        dt = time.perf_counter() - t0
        base_s = dt if base_s is None else min(base_s, dt)
    base_rate = 2 * n / base_s
    out_path = os.path.join(CACHE_DIR, "joined.bin")
    cfg = JobConfig(input_path=a_path, join_input_path=b_path,
                    output_path=out_path, backend="auto", metrics=True,
                    chunk_bytes=16 << 20, batch_size=1 << 18)
    run_job(cfg, "join")  # warm
    t0 = time.perf_counter()
    r = run_job(cfg, "join")
    secs = time.perf_counter() - t0
    got = read_join_records(out_path)
    if not all(np.array_equal(g, w) for g, w in zip(got, want)):
        return {"error": "join output parity FAILED vs NumPy sort-merge "
                         "baseline"}
    matches = int(got[0].shape[0])
    del got, want
    rate = 2 * n / secs
    return {
        "entry_name": f"join_{n // 1_000_000}mx"
                      f"{n // 1_000_000}m_rows",
        "best_s": round(secs, 3),
        "rows_per_sec": round(rate, 1),
        "matches": matches,
        "vs_baseline": round(rate / base_rate, 3),
        "cpu_baseline_rows_per_sec": round(base_rate, 1),
        "scoreboard": False,
        "note": "hash equi-join of two record corpora, full output "
                "parity vs a vectorized NumPy sort-merge (detail "
                "entry; gate-watched via metrics_snapshot)",
        "metrics_snapshot": _metrics_snapshot(r),
    }


def _bench_2proc_spill(corpus: str) -> dict:
    """``inverted_index_2proc_spill``: 2 Gloo processes build the slice
    corpus's inverted index with a resident-row cap far below the pair
    count, so every pair crosses the mesh exchange and lands in
    per-process disk buckets (--shuffle-transport auto routes to disk at
    this corpus/cap ratio).  Detail entry, not a scoreboard row: it runs
    on a forced CPU mesh (4 virtual devices per process — the same
    DCN-path harness the tests use) so the wall measures the spill
    machinery, comparable across rounds on the same host.  Parity: the
    concatenated partition files must equal the single-process artifact
    byte-for-byte after a line sort."""
    import socket
    import subprocess
    import sys as _sys

    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime import run_job

    cap_rows = 1 << 16
    single_out = os.path.join(CACHE_DIR, "spill_single.txt")
    run_job(JobConfig(input_path=corpus, output_path=single_out,
                      backend="cpu", num_shards=1, metrics=False),
            "invertedindex")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES",
              # see _launch_2proc_wordcount: a warm persistent-cache hit
              # replays a wrong-device-assignment executable in the
              # 2-process mesh and mis-routes the collectives
              "JAX_COMPILATION_CACHE_DIR"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    dist_out = os.path.join(CACHE_DIR, "spill_2proc.txt")
    metrics_out = os.path.join(CACHE_DIR, "spill_2proc_metrics.json")
    t0 = time.perf_counter()
    procs = [subprocess.Popen(
        [_sys.executable, "-m", "map_oxidize_tpu", "invertedindex", corpus,
         "--output", dist_out, "--batch-size", str(1 << 16),
         "--collect-max-rows", str(cap_rows), "--quiet",
         "--dist-coordinator", f"127.0.0.1:{port}",
         "--dist-processes", "2", "--dist-process-id", str(p),
         "--metrics-out", metrics_out],
        env=env, cwd=REPO, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT) for p in range(2)]
    try:
        for p in procs:
            p.wait(timeout=900)
    except subprocess.TimeoutExpired:
        # a lockstep wedge must not leak two spinning collective loops
        # into the rest of the bench (they would tax every later entry)
        for p in procs:
            p.kill()
        for p in procs:
            p.wait()
        return {"error": "2-proc spilled inverted index timed out "
                         "(children killed)"}
    secs = time.perf_counter() - t0
    if any(p.returncode != 0 for p in procs):
        return {"error": "2-proc spilled inverted index aborted "
                         f"(rc={[p.returncode for p in procs]})"}
    rows = []
    for i in range(2):
        with open(f"{dist_out}.part{i}of2", "rb") as f:
            rows.extend(f.read().splitlines(keepends=True))
    with open(single_out, "rb") as f:
        single = b"".join(sorted(f.read().splitlines(keepends=True)))
    if b"".join(sorted(rows)) != single:
        return {"error": "2-proc spilled inverted index parity FAILED "
                         "vs the single-process artifact"}
    snaps = []
    for i in range(2):
        with open(f"{metrics_out}.proc{i}") as f:
            doc = json.load(f)
        snaps.append(dict(doc.get("counters", {}), **doc.get("gauges", {})))
    spill_rows = sum(int(s.get("spill/rows", 0)) for s in snaps)
    if spill_rows <= 0:
        return {"error": "2-proc run past the cap never spilled"}
    tokens = sum(int(s.get("records_in", 0)) for s in snaps)
    keep = ("spill/", "demote/", "shuffle/", "compile/", "comms/",
            "heartbeat/", "dist/")
    snapshot = {k: v for k, v in snaps[0].items() if k.startswith(keep)}
    snapshot["spill/rows_global"] = spill_rows
    return {
        "best_s": round(secs, 3),
        "tokens_per_sec": round(tokens / secs, 1),
        "collect_max_rows": cap_rows,
        "transport": snaps[0].get("shuffle/transport"),
        "spilled_rows_global": spill_rows,
        "note": "2-process Gloo CPU-mesh inverted index forced past the "
                "resident cap: per-process disk-bucket spill, oracle "
                "parity enforced (detail entry; gate-watched via "
                "metrics_snapshot spill counters)",
        "metrics_snapshot": snapshot,
    }


def _launch_2proc_wordcount(corpus: str, out_path: str, metrics_out: str,
                            extra_flags: list) -> "float | str":
    """Run one 2-process Gloo CPU-mesh wordcount (the same DCN-path
    harness as ``_bench_2proc_spill``); returns wall seconds or an error
    string.  Output partitions land at ``<out_path>.part{i}of2`` and
    per-process metrics at ``<metrics_out>.proc{i}``."""
    import socket
    import subprocess
    import sys as _sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES",
              # the persistent XLA cache is poison for multi-process
              # children: a warm hit replays an executable whose device
              # assignment was baked for a DIFFERENT process's view of
              # the Gloo mesh, mis-routing the collectives (keys land on
              # wrong shards; the n_unique conservation check aborts)
              "JAX_COMPILATION_CACHE_DIR"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    t0 = time.perf_counter()
    procs = [subprocess.Popen(
        [_sys.executable, "-m", "map_oxidize_tpu", "wordcount", corpus,
         "--output", out_path, "--quiet",
         "--dist-coordinator", f"127.0.0.1:{port}",
         "--dist-processes", "2", "--dist-process-id", str(p),
         "--metrics-out", metrics_out] + extra_flags,
        env=env, cwd=REPO, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT) for p in range(2)]
    try:
        for p in procs:
            p.wait(timeout=900)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait()
        return "2-proc wordcount timed out (children killed)"
    if any(p.returncode != 0 for p in procs):
        return f"2-proc wordcount aborted (rc={[p.returncode for p in procs]})"
    return time.perf_counter() - t0


def _read_2proc_snaps(metrics_out: str) -> list:
    snaps = []
    for i in range(2):
        with open(f"{metrics_out}.proc{i}") as f:
            doc = json.load(f)
        snaps.append(dict(doc.get("counters", {}), **doc.get("gauges", {})))
    return snaps


def _comms_bytes(snaps: list) -> int:
    return sum(int(v) for s in snaps for k, v in s.items()
               if k.startswith("comms/") and k.endswith("/bytes"))


def _bench_wordcount_combined(corpus: str) -> dict:
    """``wordcount_combined``: the map-side combiner A-B on a 2-process
    pipelined-push wordcount — ON must move comms/*/bytes measurably
    DOWN (the push windows collapse duplicate keys before rows travel)
    while the output partitions stay byte-identical.  Detail entry on
    the forced CPU mesh, same harness as ``_bench_2proc_spill``."""
    runs = {}
    for mode in ("on", "off"):
        out_p = os.path.join(CACHE_DIR, f"wc_comb_{mode}.txt")
        met_p = os.path.join(CACHE_DIR, f"wc_comb_{mode}_metrics.json")
        # small merge batches so the corpus spans many exchange rounds —
        # the combiner's win IS fewer rounds (each moves a fixed buffer)
        got = _launch_2proc_wordcount(
            corpus, out_p, met_p,
            ["--shuffle-transport", "pipelined", "--push-combine", mode,
             "--batch-size", "4096", "--chunk-mb", "1"])
        if isinstance(got, str):
            return {"error": f"combiner={mode}: {got}"}
        runs[mode] = {"secs": got, "out": out_p,
                      "snaps": _read_2proc_snaps(met_p)}
    for i in range(2):
        a = open(f"{runs['on']['out']}.part{i}of2", "rb").read()
        b = open(f"{runs['off']['out']}.part{i}of2", "rb").read()
        if a != b:
            return {"error": "combiner on/off output parity FAILED "
                             f"(partition {i})"}
    bytes_on = _comms_bytes(runs["on"]["snaps"])
    bytes_off = _comms_bytes(runs["off"]["snaps"])
    if not (0 < bytes_on < bytes_off):
        return {"error": "combiner ON did not reduce comms bytes "
                         f"({bytes_on} vs OFF {bytes_off})"}
    snap_on = runs["on"]["snaps"][0]
    keep = ("shuffle/", "comms/", "pipeline/", "dist/")
    return {
        "on_s": round(runs["on"]["secs"], 3),
        "off_s": round(runs["off"]["secs"], 3),
        "comms_bytes_on": bytes_on,
        "comms_bytes_off": bytes_off,
        "comms_bytes_saved_pct": round(
            100.0 * (bytes_off - bytes_on) / bytes_off, 2),
        "push_combined_in": sum(
            int(s.get("shuffle/push_combined_in", 0))
            for s in runs["on"]["snaps"]),
        "push_combined_out": sum(
            int(s.get("shuffle/push_combined_out", 0))
            for s in runs["on"]["snaps"]),
        "note": "2-process pipelined-push wordcount, map-side combiner "
                "A-B: byte-identical output, comms bytes gated down",
        "metrics_snapshot": {k: v for k, v in snap_on.items()
                             if k.startswith(keep)},
    }


def _bench_reshard_selected(corpus: str) -> dict:
    """``reshard_selected_2proc``: the store-driven exchange-collective
    selection loop on the 2-process Gloo mesh (ISSUE-20).  Two pinned
    A-B runs — the monolithic ``all_to_all`` vs the decomposed
    ``all_gather`` + dynamic-slice resharding — must produce
    byte-identical output partitions while warming ONE calibration
    store with job evidence for both curves; a third run under ``auto``
    then reads those curves and its recorded decision (selection,
    provenance, coverage gauges, measured exchange wall) rides
    metrics_snapshot, where the ledger's selection-flip gate watches
    it.  Thin evidence records the named default-fallback — either way
    the decision fields must be present and the output identical."""
    import shutil

    calib_dir = os.path.join(CACHE_DIR, "reshard_calib")
    shutil.rmtree(calib_dir, ignore_errors=True)
    common = ["--batch-size", "4096", "--chunk-mb", "1",
              "--calib-dir", calib_dir]
    runs = {}
    for method in ("all_to_all", "all_gather"):
        out_p = os.path.join(CACHE_DIR, f"wc_resh_{method}.txt")
        met_p = os.path.join(CACHE_DIR, f"wc_resh_{method}_metrics.json")
        got = _launch_2proc_wordcount(
            corpus, out_p, met_p,
            ["--exchange-collective", method] + common)
        if isinstance(got, str):
            return {"error": f"exchange={method}: {got}"}
        runs[method] = {"secs": got, "out": out_p,
                        "snaps": _read_2proc_snaps(met_p)}
    out_auto = os.path.join(CACHE_DIR, "wc_resh_auto.txt")
    met_auto = os.path.join(CACHE_DIR, "wc_resh_auto_metrics.json")
    # one A-B pair guarantees 2 sampled latencies per method (2
    # processes x the always-sampled first exchange) whatever the
    # corpus size — floor 2 makes the selection deterministic here
    got = _launch_2proc_wordcount(
        corpus, out_auto, met_auto, common + ["--calib-min-samples", "2"])
    if isinstance(got, str):
        return {"error": f"exchange=auto: {got}"}
    snaps = _read_2proc_snaps(met_auto)
    for i in range(2):
        a = open(f"{runs['all_to_all']['out']}.part{i}of2", "rb").read()
        b = open(f"{runs['all_gather']['out']}.part{i}of2", "rb").read()
        c = open(f"{out_auto}.part{i}of2", "rb").read()
        if not (a == b == c):
            return {"error": "exchange-method output parity FAILED "
                             f"(partition {i})"}
    snap = snaps[0]
    selected = snap.get("plan/exchange_collective")
    if selected not in ("all_to_all", "all_gather"):
        return {"error": f"auto run recorded no selection ({selected!r})"}
    if snap.get("plan/exchange_collective_provenance") != "curve":
        return {"error": "auto run did not select from the warmed store "
                         f"(provenance={snap.get('plan/exchange_collective_provenance')!r})"}
    keep = ("shuffle/", "comms/", "calib/", "plan/exchange",
            "attrib/collective_wait")
    return {
        "all_to_all_s": round(runs["all_to_all"]["secs"], 3),
        "all_gather_s": round(runs["all_gather"]["secs"], 3),
        "auto_s": round(got, 3),
        "selected": selected,
        "selected_provenance": snap.get(
            "plan/exchange_collective_provenance"),
        "calib_coverage_pct": snap.get("calib/coverage_pct"),
        "collective_wait_ms": snap.get("attrib/collective_wait_ms"),
        "note": "2-process exchange-collective A-B + store-driven auto "
                "selection: byte-identical partitions across all three "
                "runs, decision + coverage + exchange wall gated via "
                "metrics_snapshot",
        "metrics_snapshot": {k: v for k, v in snap.items()
                             if k.startswith(keep)},
    }


def _bench_2proc_pipelined(corpus: str) -> dict:
    """``skewed_reduce_2proc_pipelined``: a 2-process reduce over a
    hot-key-skewed corpus under the push transport — the shuffle wall
    the critpath's ``map_shuffle_overlapped`` what-if predicted hides
    behind map.  Gates: byte parity vs the barrier (hbm) transport and
    ``pipeline/shuffle_overlap_ratio`` > 0 on every process; the
    ``shuffle/push_*`` counters ride metrics_snapshot for the ledger."""
    skew_path = os.path.join(CACHE_DIR, "skewed_wc.txt")
    if not os.path.isfile(skew_path):
        # ~16MB, one hot key at ~50% mass plus a 512-word tail: the shape
        # where eager pushes matter (the hot partition dominates rounds);
        # big enough that each process maps several 1MB chunks, so the
        # producer genuinely runs ahead of the lockstep exchange
        rng = np.random.default_rng(7)
        words = np.array([b"hotkey"] + [f"w{i:04d}".encode()
                                        for i in range(512)], dtype=object)
        draw = rng.integers(0, 513, (160_000, 16))
        draw[:, ::2] = 0  # every other slot is the hot key
        with open(skew_path, "wb") as f:
            for row in words[draw]:
                f.write(b" ".join(row) + b"\n")
    runs = {}
    # combiner OFF here on purpose: this entry isolates the push
    # pipeline's overlap (merge rounds interleaving with production —
    # ON would collapse the low-vocab skew to one end-of-stream round);
    # wordcount_combined is the combiner's own A-B
    base = ["--batch-size", "2048", "--chunk-mb", "1",
            "--push-combine", "off"]
    for name, flags in (("hbm", base + ["--shuffle-transport", "hbm"]),
                        ("pipelined",
                         base + ["--shuffle-transport", "pipelined"])):
        out_p = os.path.join(CACHE_DIR, f"wc_skew_{name}.txt")
        met_p = os.path.join(CACHE_DIR, f"wc_skew_{name}_metrics.json")
        got = _launch_2proc_wordcount(skew_path, out_p, met_p, flags)
        if isinstance(got, str):
            return {"error": f"{name}: {got}"}
        runs[name] = {"secs": got, "out": out_p,
                      "snaps": _read_2proc_snaps(met_p)}
    for i in range(2):
        a = open(f"{runs['hbm']['out']}.part{i}of2", "rb").read()
        b = open(f"{runs['pipelined']['out']}.part{i}of2", "rb").read()
        if a != b:
            return {"error": "pipelined vs barrier transport parity "
                             f"FAILED (partition {i})"}
    snaps = runs["pipelined"]["snaps"]
    ratios = [float(s.get("pipeline/shuffle_overlap_ratio", 0.0))
              for s in snaps]
    # gate on the max: chunks round-robin across the 2 processes, so the
    # process holding fewer rounds can legitimately sit at ~0 overlap
    if max(ratios) <= 0.0:
        return {"error": "push pipeline never overlapped "
                         f"(shuffle_overlap_ratio={ratios})"}
    if not all(int(s.get("shuffle/push_rounds", 0)) > 0 for s in snaps):
        return {"error": "pipelined run recorded no push rounds"}
    keep = ("shuffle/", "comms/", "pipeline/", "dist/", "critpath/")
    return {
        "best_s": round(runs["pipelined"]["secs"], 3),
        "barrier_s": round(runs["hbm"]["secs"], 3),
        "overlap_ratio": [round(r, 4) for r in ratios],
        "push_rounds": sum(int(s.get("shuffle/push_rounds", 0))
                           for s in snaps),
        "push_rows": sum(int(s.get("shuffle/push_rows", 0))
                         for s in snaps),
        "transport": snaps[0].get("shuffle/transport"),
        "note": "2-process skewed reduce, push transport vs barrier: "
                "byte parity + nonzero shuffle overlap gated",
        "metrics_snapshot": {k: v for k, v in snaps[0].items()
                             if k.startswith(keep)},
    }


def _bench_serve(corpus: str, n_jobs: int = 6) -> dict:
    """``serve_warm_small_jobs``: submit ``n_jobs`` identical small
    wordcounts to an in-process resident server back to back.

    Job 1 is the COLD job (it pays whatever XLA compiles this process
    still owes); every later job must show a ZERO per-job ``compile/*``
    delta — the per-job compile-ledger accounting enforces it here AND
    in the ledger gate (the compile counters ride the entry's
    metrics_snapshot, where any later increase fails ``--gate``).  The
    entry also records where warm p50 job time goes: ``warm_setup_frac``
    is the share of wall OUTSIDE the driver's measured phases (process/
    scheduler/dispatch plumbing) — the acceptance bar is that warm
    latency is dominated by the compute phases, not setup."""
    import shutil

    from map_oxidize_tpu.config import ServeConfig
    from map_oxidize_tpu.serve.server import ResidentServer

    spool = os.path.join(CACHE_DIR, "serve_spool")
    shutil.rmtree(spool, ignore_errors=True)
    srv = ResidentServer(ServeConfig(
        port=0, workers=1, spool_dir=spool,
        ledger_dir="none",      # bench owns the ledger entries it gates
    ).validate()).start()
    times: list[float] = []
    compiles: list[int] = []
    summaries: list[dict] = []
    try:
        for i in range(n_jobs):
            t0 = time.perf_counter()
            job = srv.submit("wordcount", corpus)
            done = srv.wait(job.id, timeout=600)
            dt = time.perf_counter() - t0
            if done.state != "done":
                return {"error": f"serve job {i} {done.state}: "
                                 f"{done.reason}"}
            times.append(dt)
            compiles.append(int(done.summary.get(
                "compile/total_compiles", 0)))
            summaries.append(done.summary)
    finally:
        srv.shutdown()
    # median WARM job by wall clock; its own summary provides the phase
    # split, so warm_setup_frac compares one job's phases to that same
    # job's wall (mixing jobs could hide real setup overhead behind a
    # slow last job)
    warm_idx = sorted(range(1, len(times)), key=times.__getitem__)
    mi = warm_idx[len(warm_idx) // 2]
    warm_p50 = times[mi]
    if any(compiles[1:]):
        return {"error": f"warm serve jobs recompiled: per-job compile "
                         f"deltas {compiles} (job 1 may compile, later "
                         "jobs must not)"}
    median = summaries[mi]
    words = int(median.get("records_in", 0))
    phases = {k: round(v, 4) for k, v in median.items()
              if k.startswith("time/") and k.endswith("_s")}
    phase_total = sum(phases.values())
    entry = {
        "jobs": n_jobs,
        "cold_s": round(times[0], 3),
        "warm_p50_s": round(warm_p50, 3),
        "warm_runs_s": [round(t, 3) for t in times[1:]],
        "cold_over_warm": round(times[0] / warm_p50, 3),
        "words_per_sec": round(words / warm_p50, 1),
        "per_job_compile_deltas": compiles,
        "warm_zero_compile_delta": True,
        "warm_phases_s": phases,
        # share of warm wall outside the driver's phases: submit/queue/
        # scheduler plumbing — the "setup" the resident server exists to
        # amortize away (phases == device-feeding compute work)
        "warm_setup_frac": round(
            max(1.0 - phase_total / warm_p50, 0.0), 4),
        "scoreboard": False,     # a latency record, not a vs-CPU ratio
        "note": "N identical small wordcounts through the resident "
                "server; compile/* deltas are zero from job 2 on "
                "(gate-enforced via metrics_snapshot)",
        "metrics_snapshot": {k: v for k, v in median.items()
                             if k.startswith(("compile/", "xprof/",
                                              "time/", "pipeline/",
                                              "heartbeat/"))},
    }
    return entry


if __name__ == "__main__":
    raise SystemExit(main())
