#!/usr/bin/env python
"""Headline benchmark: end-to-end word-count throughput (words/sec/chip).

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}``.

``vs_baseline`` is the speedup over the measured CPU reference baseline — a
single-threaded host run of the reference program's exact semantics
(tokenize per ``/root/reference/src/main.rs:94-101``, merge per
main.rs:131-134; see ``workloads/reference_model.py``).  The reference
publishes no numbers and its corpus was stripped (SURVEY.md §6), so the
baseline is measured here, on this machine, on the same corpus — and top-k
parity between the two runs is asserted, so the speedup is apples-to-apples.

Corpus: deterministic synthetic Zipf text (seeded), cached under
``.bench_cache/``.  Size via ``MOXT_BENCH_MB`` (default 64; the baseline is
timed on a capped slice and rate-extrapolated since single-thread Python is
O(minutes) at 10x that size).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

CACHE_DIR = os.path.join(REPO, ".bench_cache")
BENCH_MB = int(os.environ.get("MOXT_BENCH_MB", "64"))
BASELINE_CAP_MB = int(os.environ.get("MOXT_BENCH_BASELINE_CAP_MB", "8"))
TOP_K = 10


def make_corpus(path: str, target_mb: int) -> None:
    """Deterministic Zipf corpus: 30k-word vocab (mixed case + punctuation
    variants so the lowercase/no-strip semantics matter), ~12 words/line."""
    rng = np.random.default_rng(1234)
    v = 30_000
    alphabet = np.frombuffer(b"abcdefghijklmnopqrstuvwxyz", np.uint8)
    lengths = rng.integers(2, 11, size=v)
    vocab = []
    for i, L in enumerate(lengths):
        w = bytes(rng.choice(alphabet, size=int(L)).tobytes())
        r = i % 10
        if r == 7:
            w = w.capitalize()          # exercises lowercasing
        elif r == 8:
            w = w + b","                # punctuation kept, distinct key
        elif r == 9:
            w = w + b"."
        vocab.append(w)
    vocab = np.array(vocab, dtype=object)
    # Zipf-ish rank weights (s=1.1), the realistic word-frequency shape
    ranks = np.arange(1, v + 1, dtype=np.float64)
    p = ranks ** -1.1
    p /= p.sum()

    target = target_mb * 1024 * 1024
    tmp = path + ".tmp"
    written = 0
    with open(tmp, "wb") as f:
        while written < target:
            toks = rng.choice(vocab, size=1_000_000, p=p)
            lines = []
            for i in range(0, 1_000_000, 12):
                lines.append(b" ".join(toks[i:i + 12]))
            blob = b"\n".join(lines) + b"\n"
            f.write(blob)
            written += len(blob)
    os.replace(tmp, path)


def main() -> int:
    logging.disable(logging.INFO)  # keep stdout/stderr quiet; one JSON line
    os.makedirs(CACHE_DIR, exist_ok=True)
    corpus = os.path.join(CACHE_DIR, f"zipf_{BENCH_MB}mb.txt")
    if not os.path.isfile(corpus):
        make_corpus(corpus, BENCH_MB)

    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime import run_job
    from map_oxidize_tpu.workloads.reference_model import top_k_model, wordcount_model

    # --- our pipeline (device engine on whatever chip jax offers first)
    cfg = JobConfig(
        input_path=corpus,
        output_path=os.path.join(CACHE_DIR, "final_result.txt"),
        backend="auto",
        top_k=TOP_K,
        metrics=False,
    )
    # warm the XLA cache so compile time isn't billed as throughput
    run_job(
        JobConfig(input_path=corpus, output_path="", backend="auto",
                  metrics=False, chunk_bytes=cfg.chunk_bytes), "wordcount"
    ) if os.environ.get("MOXT_BENCH_WARM", "1") == "1" else None
    t0 = time.perf_counter()
    result = run_job(cfg, "wordcount")
    ours_s = time.perf_counter() - t0
    words = result.metrics["records_in"]
    ours_rate = words / ours_s

    # --- CPU reference baseline: single-thread, reference semantics, on a
    # capped slice of the same corpus (rate-extrapolated; it's O(n))
    cap = BASELINE_CAP_MB * 1024 * 1024
    with open(corpus, "rb") as f:
        slice_bytes = f.read(cap)
    slice_bytes = slice_bytes[: slice_bytes.rfind(b"\n") + 1]
    t0 = time.perf_counter()
    base_counts = wordcount_model([slice_bytes])
    base_s = time.perf_counter() - t0
    base_words = sum(base_counts.values())
    base_rate = base_words / base_s

    # --- parity: our top-k on the slice must equal the model's
    slice_cfg = JobConfig(input_path=corpus, output_path="", backend="auto",
                          metrics=False, top_k=TOP_K)
    if BENCH_MB * 1024 * 1024 <= cap:
        slice_res = result
    else:
        tmp_slice = os.path.join(CACHE_DIR, "slice.txt")
        with open(tmp_slice, "wb") as f:
            f.write(slice_bytes)
        slice_cfg.input_path = tmp_slice
        slice_res = run_job(slice_cfg, "wordcount")
    want_top = top_k_model(base_counts, TOP_K)
    if slice_res.top[:TOP_K] != want_top:
        print(json.dumps({"error": "top-k parity FAILED vs reference model"}))
        return 1

    print(json.dumps({
        "metric": "wordcount_words_per_sec_per_chip",
        "value": round(ours_rate, 1),
        "unit": "words/sec",
        "vs_baseline": round(ours_rate / base_rate, 3),
        "detail": {
            "corpus_mb": BENCH_MB,
            "words": int(words),
            "end_to_end_s": round(ours_s, 3),
            "cpu_baseline_words_per_sec": round(base_rate, 1),
            "distinct_keys": int(result.metrics["distinct_keys"]),
        },
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
