"""A user-defined workload on the Mapper/Reducer API.

The north star names a *pluggable* Mapper/Reducer boundary — the reference
hardcodes its workload (``count_words`` at ``main.rs:94-101`` with the merge
loop at 131-134).  This example plugs a new workload into the framework's
engines without touching framework code: **minimum temperature by city**
over CSV-ish lines ``city,temperature``.

    map:    line -> (hash(city), temp_int)
    reduce: min  (a named monoid — the device folds with segment_min and,
            sharded, the same monoid after the all_to_all exchange)

Run it:

    python examples/custom_workload.py /path/to/readings.txt

The same mapper runs unchanged on the single-chip engine or the sharded
mesh engine — engine choice is a config knob, not a code change.
"""

from __future__ import annotations

import sys

import numpy as np

from map_oxidize_tpu.api import Mapper, MapOutput, MinReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.ops.hashing import HashDictionary, moxt64_bytes, split_u64
from map_oxidize_tpu.runtime.driver import run_wordcount_job


class MinTempMapper(Mapper):
    """``city,temp`` lines -> one (city_hash, min_temp) row per city seen in
    the chunk (an in-chunk combiner, like the built-in word count)."""

    value_shape = ()
    value_dtype = np.int32
    keys_have_dictionary = True

    def map_chunk(self, chunk: bytes) -> MapOutput:
        if not isinstance(chunk, bytes):
            chunk = bytes(chunk)
        best: dict[bytes, int] = {}
        n = 0
        for line in chunk.split(b"\n"):
            if not line:
                continue
            city, _, temp = line.partition(b",")
            try:
                t = int(temp)
            except ValueError:
                continue  # malformed line: skipped, like main.rs:160
            if not -(1 << 31) <= t < (1 << 31):
                continue  # out of the int32 value range: also malformed
            n += 1
            prev = best.get(city)
            if prev is None or t < prev:
                best[city] = t
        d = HashDictionary()
        hashes = np.empty(len(best), np.uint64)
        values = np.empty(len(best), np.int32)
        for i, (city, t) in enumerate(best.items()):
            h = moxt64_bytes(city)
            d.add(h, city)
            hashes[i] = h
            values[i] = t
        hi, lo = split_u64(hashes)
        return MapOutput(hi=hi, lo=lo, values=values, dictionary=d,
                         records_in=n)


def run(path: str, num_shards: int = 1):
    cfg = JobConfig(input_path=path, output_path="", num_shards=num_shards,
                    metrics=False)
    # run_wordcount_job is the generic scalar-valued driver; the name keeps
    # the reference lineage (its only workload), the signature does not
    result = run_wordcount_job(cfg, MinTempMapper(), MinReducer())
    return result.counts


def run_device_topk(path: str, k: int = 5, num_shards: int = 1):
    """The engine-level DEVICE top-k on a min monoid: the k warmest city
    minima, selected by ``lax.top_k`` on-chip (padding masked to the dtype
    floor — a min identity is the dtype MAX and is never a winner).
    Demonstrates that user monoids get the same device report path as the
    built-in sum workloads."""
    from map_oxidize_tpu.io.splitter import iter_chunks
    from map_oxidize_tpu.ops.hashing import join_u64
    from map_oxidize_tpu.runtime.driver import make_engine

    cfg = JobConfig(input_path=path, output_path="", num_shards=num_shards,
                    metrics=False)
    mapper = MinTempMapper()
    engine = make_engine(cfg, MinReducer())
    dictionary = HashDictionary()
    for chunk in iter_chunks(path, cfg.chunk_bytes):
        out = mapper.map_chunk(bytes(chunk))
        dictionary.update(out.dictionary)
        engine.feed(out)
    t_hi, t_lo, t_vals, n = engine.top_k(k)
    m = min(k, n)  # rows past the live count are SENTINEL padding
    lookup = dictionary.lookup
    return [(lookup(int(h)), int(v))
            for h, v in zip(join_u64(t_hi[:m], t_lo[:m]).tolist(),
                            np.asarray(t_vals)[:m].tolist())], n


if __name__ == "__main__":
    counts = run(sys.argv[1])
    for city, t in sorted(counts.items(), key=lambda kv: kv[1])[:10]:
        print(f"{city.decode()}: {t}")
    top, n = run_device_topk(sys.argv[1])
    print(f"device top-{len(top)} warmest minima (of {n} cities):")
    for city, t in top:
        print(f"{city.decode()}: {t}")
