#!/usr/bin/env python
"""Submit jobs to a running resident server and watch them finish.

Start a server first (any terminal):

    python -m map_oxidize_tpu serve --port 8321

then:

    python examples/submit_jobs.py --url http://127.0.0.1:8321 corpus.txt

The script submits the same small wordcount N times back to back and
prints each job's latency and per-job compile delta — on a warm server
every job after the first reports ``compiles: 0`` (the whole point of
resident serving), and the cold/warm latency ratio shows what one
process's warm XLA caches are worth.  It finishes with one deliberately
oversized submission to show a named admission rejection.
"""

from __future__ import annotations

import argparse
import sys
import time

from map_oxidize_tpu.serve.client import ServeClient, ServeError


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default="http://127.0.0.1:8321")
    ap.add_argument("corpus", help="SERVER-local corpus path")
    ap.add_argument("--jobs", type=int, default=4)
    args = ap.parse_args()

    client = ServeClient(args.url)
    times = []
    for i in range(args.jobs):
        t0 = time.perf_counter()
        try:
            job = client.submit("wordcount", args.corpus,
                                config={"num_shards": 1}, deadline_s=300)
        except ServeError as e:
            print(f"submit refused: {e}", file=sys.stderr)
            return 2
        if job["state"] == "rejected":
            print(f"{job['id']} rejected: {job['reason']}",
                  file=sys.stderr)
            return 3
        done = client.wait(job["id"], timeout_s=600)
        dt = time.perf_counter() - t0
        times.append(dt)
        print(f"{done['id']}: {done['state']} in {dt:.3f}s  "
              f"records={done.get('records_in')}  "
              f"compiles={done.get('compiles')}"
              + ("   <- cold job (pays the compiles)" if i == 0 else ""))
    if len(times) > 2:
        warm = sorted(times[1:])[len(times[1:]) // 2]
        print(f"cold {times[0]:.3f}s vs warm p50 {warm:.3f}s "
              f"({times[0] / warm:.1f}x) — the resident-server win")

    # admission control: an impossible working set is REJECTED by name,
    # not accepted and crashed mid-run.  (Backends without memory stats —
    # CPU — leave admission open, so there the probe just runs.)
    big = client.submit("wordcount", args.corpus,
                        est_hbm_bytes=1 << 60)
    reason = (big.get("reason")
              or "(no HBM budget on this backend: admission open)")
    print(f"oversized probe -> {big['state']}: {reason}")

    table = client.jobs()
    print(f"server: {table['counts']} queue {table['queue']['depth']}/"
          f"{table['queue']['max']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
