"""A vector-valued user workload: MEAN temperature by city.

The min-temperature example (`custom_workload.py`) shows a scalar monoid;
this one shows the other half of the Reducer surface: **vector values**.
"Mean" is not a monoid, but (sum, count) is — each mapped row carries the
value ``[temp_sum, n]`` and the engine's vector segment-sum folds both
components at once (the same machinery k-means uses for its
``[Σx, n]`` centroid rows).  The mean falls out at readback.

    map:    city,temp line -> (hash(city), [temp, 1])
    reduce: component-wise sum over value_shape=(2,)
    report: sums[:, 0] / sums[:, 1]

Run it:

    python examples/vector_values.py /path/to/readings.txt

Like every workload, it runs unchanged on the single-chip engine or the
sharded mesh engine (``num_shards``).
"""

from __future__ import annotations

import sys

import numpy as np

from map_oxidize_tpu.api import Mapper, MapOutput, SumReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.io.splitter import iter_chunks
from map_oxidize_tpu.ops.hashing import (
    SENTINEL,
    HashDictionary,
    join_u64,
    moxt64_bytes,
    split_u64,
)
from map_oxidize_tpu.runtime.driver import make_engine


class MeanTempMapper(Mapper):
    """``city,temp`` lines -> one (city_hash, [temp_sum, count]) row per
    city seen in the chunk (in-chunk combining, like the built-ins)."""

    value_shape = (2,)
    value_dtype = np.float32
    keys_have_dictionary = True
    conserves_counts = False  # values are measurements, not counts

    def map_chunk(self, chunk) -> MapOutput:
        if not isinstance(chunk, bytes):
            chunk = bytes(chunk)
        sums: dict[bytes, float] = {}
        counts: dict[bytes, int] = {}
        n = 0
        for line in chunk.split(b"\n"):
            city, _, temp = line.partition(b",")
            try:
                t = float(temp)
            except ValueError:
                continue  # malformed line: skipped, like main.rs:160
            n += 1
            sums[city] = sums.get(city, 0.0) + t
            counts[city] = counts.get(city, 0) + 1
        d = HashDictionary()
        hashes = np.empty(len(sums), np.uint64)
        values = np.empty((len(sums), 2), np.float32)
        for i, (city, s) in enumerate(sums.items()):
            h = moxt64_bytes(city)
            d.add(h, city)
            hashes[i] = h
            values[i, 0] = s
            values[i, 1] = counts[city]
        hi, lo = split_u64(hashes)
        return MapOutput(hi=hi, lo=lo, values=values, dictionary=d,
                         records_in=n)


def run(path: str, num_shards: int = 1) -> dict[bytes, float]:
    cfg = JobConfig(input_path=path, output_path="", num_shards=num_shards,
                    metrics=False)
    mapper = MeanTempMapper()
    engine = make_engine(cfg, SumReducer(), value_shape=(2,),
                         value_dtype=np.float32)
    dictionary = HashDictionary()
    for chunk in iter_chunks(path, cfg.chunk_bytes):
        out = mapper.map_chunk(chunk)
        dictionary.update(out.dictionary)
        engine.hint_total_keys(dictionary.upper_bound())
        engine.feed(out)
    hi, lo, vals, n = engine.finalize()
    hi, lo, vals = np.asarray(hi), np.asarray(lo), np.asarray(vals)
    live = ~((hi == np.uint32(SENTINEL)) & (lo == np.uint32(SENTINEL)))
    k64 = join_u64(hi[live], lo[live])
    v = vals[live]
    assert k64.shape[0] == n
    lookup = dictionary.lookup
    return {lookup(int(h)): float(s) / c
            for h, (s, c) in zip(k64.tolist(), v.tolist())}


if __name__ == "__main__":
    means = run(sys.argv[1])
    for city, m in sorted(means.items()):
        print(f"{city.decode()}: {m:.2f}")
